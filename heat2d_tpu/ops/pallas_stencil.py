"""Pallas/Mosaic TPU stencil kernels — the grad1612_cuda_heat.cu analogue.

The reference's CUDA path (grad1612_cuda_heat.cu:55-62 ``update`` kernel,
:82-85 ping-pong launch loop) maps one GPU thread to one cell and enqueues
two kernel launches per loop iteration from the host. The TPU-native design
inverts that: the *loop* lives on the device and the kernel owns *tiles*,
not cells:

- ``multi_step_vmem`` — whole-grid-in-VMEM kernel that runs many time steps
  per invocation (double buffering is a functional ``fori_loop`` carry in
  vector memory, replacing the CUDA pointer swap). One launch ≈ thousands
  of CUDA launches, zero HBM traffic between steps. Used when the grid fits
  the VMEM budget — covers the reference's own CUDA configs (640×1024 =
  2.5 MB).
- ``band_step`` — streaming one-step kernel for HBM-resident grids: the
  grid of programs walks row bands; each band reads its (bm, ny) block plus
  two precomputed neighbor-row strips (the intra-chip halo — the VMEM-tile
  analogue of the device-level ppermute halo), updates, and masks the
  global boundary in-register. Host-side strip extraction touches ~2 rows
  per band per step — negligible next to the band traffic itself.

Unlike the reference kernel, which computes per-cell in *double* (CUDA
promotes through the 2.0/0.1 literals — SURVEY.md Appendix B) and whose
result is vacuous anyway (A.1), these kernels compute in float32 (TPU has
no fast f64; parity tests run the golden model) and are verified against
the jnp golden model in interpreter mode and on-device.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from heat2d_tpu.config import ConfigError
from heat2d_tpu.models import engine
from heat2d_tpu.ops.stencil import residual_sq
from heat2d_tpu.utils.profiling import phase

#: Per-core VMEM for device kinds we know; anything else falls back to the
#: measured v5e envelope. The reference queried its device the same way
#: (detailsGPU, grad1612_cuda_heat.cu:24-37) instead of baking in one card.
_KNOWN_VMEM_TOTAL_BYTES = {
    # MEASURED (tune_bands.py probe on the attached chip): v5e/v5 lite —
    # the 16 MB figure reproduces the observed compile envelope exactly.
    "TPU v5 lite": 16 * 1024 * 1024,
    "TPU v5e": 16 * 1024 * 1024,
    # ASSUMED from public specs, NOT probed: held at the conservative
    # 16 MB even where the part likely has more (v4's 32 MB) — this
    # number sets the fast-fail hard limit, and an overestimate
    # re-exposes the opaque Mosaic scoped-VMEM OOM the check exists to
    # prevent. Probe with benchmarks/tune_bands.py on real hardware and
    # raise per kind (or per run via --vmem-budget).
    "TPU v2": 16 * 1024 * 1024,
    "TPU v3": 16 * 1024 * 1024,
    "TPU v4": 16 * 1024 * 1024,
}
_FALLBACK_VMEM_TOTAL_BYTES = 16 * 1024 * 1024

#: Explicit overrides (``--vmem-budget`` / set_vmem_budget). ``None`` means
#: derive from the detected device. Tests monkeypatch VMEM_BUDGET_BYTES
#: directly to force routing decisions.
VMEM_BUDGET_BYTES: int | None = None
VMEM_HARD_LIMIT_BYTES: int | None = None
#: Human-readable origin of an explicit hard limit, for the fast-fail
#: message (set_vmem_budget and the tune_bands probe each stamp their
#: own — so a probe failure doesn't misreport as a --vmem-budget issue).
VMEM_LIMIT_ORIGIN: str | None = None
#: Which source set the active budget — "default" (derived from the
#: detected device), "flag" (--vmem-budget), "env" (HEAT2D_VMEM_BUDGET),
#: "probe" (a tune probe's lifted limit, tune.measure.probe_limits), or
#: "db" (a tuning db's probed vmem stamp). Surfaced in run records.
VMEM_BUDGET_SOURCE: str = "default"

#: Env override for the per-core VMEM total, in MiB (the --vmem-budget
#: flag's units) — applied lazily at the first budget query so library
#: embedders get it without CLI plumbing.
_ENV_BUDGET_VAR = "HEAT2D_VMEM_BUDGET"
_env_budget_checked = False

_detected: tuple[int, str] | None = None


def _maybe_env_budget() -> None:
    """Apply the HEAT2D_VMEM_BUDGET env override once, unless an
    explicit set_vmem_budget (flag/db/test monkeypatch) already won.
    A malformed value raises on EVERY query (the checked flag is only
    set on success): raising once and then silently serving the
    default would let a typo'd cap masquerade as applied."""
    global _env_budget_checked
    if _env_budget_checked or VMEM_BUDGET_BYTES is not None:
        return
    v = os.environ.get(_ENV_BUDGET_VAR)
    if not v:
        _env_budget_checked = True
        return
    try:
        set_vmem_budget(int(v) * 1024 * 1024, source="env",
                        origin=f"set by the {_ENV_BUDGET_VAR} env "
                               f"override")
    except (ValueError, ConfigError) as e:
        raise ConfigError(
            f"{_ENV_BUDGET_VAR}={v!r} is not a valid per-core VMEM "
            f"size in MiB: {e}") from e
    _env_budget_checked = True


def vmem_budget_source() -> str:
    """Provenance of the active VMEM planning budget (run records'
    ``vmem_budget.source``)."""
    _maybe_env_budget()
    if VMEM_BUDGET_BYTES is None and VMEM_HARD_LIMIT_BYTES is None:
        return "default"
    return VMEM_BUDGET_SOURCE


def _vmem_total() -> tuple[int, str]:
    """(total VMEM bytes/core, device kind), detected lazily — querying
    devices at import time would initialize the backend before
    jax.distributed.initialize can run (parallel/multihost.py)."""
    global _detected
    if _detected is None:
        try:
            kind = getattr(jax.devices()[0], "device_kind", "unknown")
        except Exception:  # pragma: no cover - no backend at all
            kind = "unknown"
        _detected = (_KNOWN_VMEM_TOTAL_BYTES.get(
            kind, _FALLBACK_VMEM_TOTAL_BYTES), kind)
    return _detected


def vmem_budget_bytes() -> int:
    """Working-set budget for the VMEM-resident kernel (carry +
    temporaries): half the core's VMEM, leaving the rest for the
    compiler's own buffers."""
    _maybe_env_budget()
    if VMEM_BUDGET_BYTES is not None:
        return VMEM_BUDGET_BYTES
    total, _ = _vmem_total()
    return total // 2


def vmem_hard_limit_bytes() -> int:
    """Ceiling for the estimated per-program band working set before we
    refuse to compile: total minus ~2 MB of compiler headroom. On the
    v5e this lands at 14 MB; the largest config proven to compile there
    (4096-wide rows, bm=128, T=8) estimates ~11.8 MB."""
    _maybe_env_budget()
    if VMEM_HARD_LIMIT_BYTES is not None:
        return VMEM_HARD_LIMIT_BYTES
    total, _ = _vmem_total()
    return total - 2 * 1024 * 1024


def set_vmem_budget(total_bytes: int, source: str = "flag",
                    origin: str | None = None) -> None:
    """Override the detected per-core VMEM size (the --vmem-budget flag,
    the HEAT2D_VMEM_BUDGET env, or a tuning db's probed stamp): budget
    and hard limit re-derive from the given total; ``source``/``origin``
    stamp the provenance run records and fast-fail messages report."""
    global VMEM_BUDGET_BYTES, VMEM_HARD_LIMIT_BYTES, VMEM_LIMIT_ORIGIN
    global VMEM_BUDGET_SOURCE
    if total_bytes < 4 * 1024 * 1024:
        raise ConfigError(
            f"--vmem-budget must be at least 4 MiB, got {total_bytes} bytes")
    VMEM_BUDGET_BYTES = total_bytes // 2
    VMEM_HARD_LIMIT_BYTES = total_bytes - 2 * 1024 * 1024
    VMEM_LIMIT_ORIGIN = origin or "set by the --vmem-budget override"
    VMEM_BUDGET_SOURCE = source


def _interpret() -> bool:
    """Interpreter mode off-TPU (tests on the virtual CPU mesh)."""
    return jax.default_backend() != "tpu"


def _step_value(u, cx, cy):
    """One clamped-boundary time step on an array *value* (in-kernel).

    Uses the FMA-friendly factoring ``(1-2cx-2cy)*u + cx*(N+S) + cy*(E+W)``
    — algebraically equal to the reference expression but mapping to 3
    multiply-adds on the VPU (+24% measured on the VPU-bound band kernel
    at 4096x4096; differs from the literal form only at f32-ulp level,
    same class as the f32-vs-double deviation the fast path already has —
    SURVEY.md Appendix B; the bitwise-parity paths use the literal form).
    Reassembles via concatenation rather than ``.at[].set`` — Mosaic has no
    scatter lowering, and concatenation of static slices vectorizes
    cleanly.
    """
    c = u[1:-1, 1:-1]
    k0 = 1.0 - 2.0 * cx - 2.0 * cy
    new = (k0 * c
           + cx * (u[2:, 1:-1] + u[:-2, 1:-1])
           + cy * (u[1:-1, 2:] + u[1:-1, :-2]))
    mid = jnp.concatenate([u[1:-1, :1], new, u[1:-1, -1:]], axis=1)
    return jnp.concatenate([u[:1, :], mid, u[-1:, :]], axis=0)


def _step_value_literal(u, cx, cy):
    """One clamped-boundary step, literal reference expression
    ``c + cx*(N+S-2c) + cy*(E+W-2c)`` (grad1612_cuda_heat.cu:59-61) — the
    all-f32 evaluation order of ops.stencil._laplacian_update, so shard
    kernels using it stay BITWISE identical to the golden jnp path (the
    hybrid-vs-serial tests assert exact equality; mode='pallas' uses the
    faster FMA factoring in _step_value instead)."""
    c = u[1:-1, 1:-1]
    new = (c
           + cx * ((u[2:, 1:-1] + u[:-2, 1:-1]) - 2.0 * c)
           + cy * ((u[1:-1, 2:] + u[1:-1, :-2]) - 2.0 * c))
    mid = jnp.concatenate([u[1:-1, :1], new, u[1:-1, -1:]], axis=1)
    return jnp.concatenate([u[:1, :], mid, u[-1:, :]], axis=0)


# --------------------------------------------------------------------- #
# Kernel A: VMEM-resident multi-step
# --------------------------------------------------------------------- #

#: Unroll factor for the kernels' in-VMEM step loops. Unrolling lets
#: Mosaic schedule across steps (fusing each step's select/reassembly
#: into the next step's reads): measured 143->195 Gcells/s at 4096^2 on
#: the v5e band kernel. Bounded so a 10k-step resident run doesn't
#: replicate the body 10k times at compile.
_STEP_UNROLL = 8


def _unrolled_steps(steps: int, one, v):
    """``one`` applied ``steps`` (static) times, bodies inlined in groups
    of _STEP_UNROLL. Mosaic's fori lowering accepts only full unroll or
    none, so the partial unroll is done by hand: a rolled outer loop
    whose body is _STEP_UNROLL inlined steps. The remainder runs as a
    ROLLED loop, not inlined: bodies inlined outside a loop keep every
    step's temporaries live at once (a 2-step remainder of the 8192-wide
    shard kernel allocated 17.7 MB of VMEM stack and failed to compile
    where the 8-step looped body fit), and remainder sweeps are a
    once-per-chunk tail where the unroll win is irrelevant anyway.
    """
    full, rem = divmod(steps, _STEP_UNROLL)
    if full:
        def body(_, w):
            for _ in range(_STEP_UNROLL):
                w = one(w)
            return w
        v = lax.fori_loop(0, full, body, v, unroll=False)
    if rem:
        v = lax.fori_loop(0, rem, lambda _, w: one(w), v, unroll=False)
    return v


def _vmem_kernel(u_ref, out_ref, *, steps, cx, cy, step):
    u = u_ref[:]
    out_ref[:] = _unrolled_steps(steps, lambda v: step(v, cx, cy), u)


def fits_vmem(shape, dtype=jnp.float32) -> bool:
    nbytes = shape[0] * shape[1] * jnp.dtype(dtype).itemsize
    return 3 * nbytes <= vmem_budget_bytes()


def multi_step_vmem(u, steps: int, cx: float, cy: float,
                    step=_step_value):
    """Run ``steps`` time steps in one kernel, grid resident in VMEM."""
    mspace, _ = _mem_spaces()
    kwargs = dict(in_specs=[pl.BlockSpec(**mspace)],
                  out_specs=pl.BlockSpec(**mspace))
    return pl.pallas_call(
        functools.partial(_vmem_kernel, steps=steps, cx=cx, cy=cy,
                          step=step),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        interpret=_interpret(),
        input_output_aliases={0: 0},
        **kwargs)(u)


# --------------------------------------------------------------------- #
# Kernel B: streaming row-band one-step
# --------------------------------------------------------------------- #

def _band_kernel(up_ref, u_ref, dn_ref, out_ref, *, bm, nx, ny, cx, cy,
                 step, hi_start=None):
    i = pl.program_id(0)
    ext = jnp.concatenate([up_ref[0], u_ref[:], dn_ref[0]], axis=0)
    # The step form handles the column boundary (first/last col kept);
    # its kept first/last *rows* here are strip rows, discarded by the
    # [1:-1] slice — the band's own rows all come out updated.
    new = step(ext, cx, cy)[1:-1, :]

    def write_masked():
        # Global first/last row are boundary: keep (CUDA guard
        # ix>0 && ix<NX-1, grad1612_cuda_heat.cu:58).
        gi = i * bm + lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
        # >= nx-1 (not ==) also holds plan_bands pad rows inert at zero,
        # the same invariant kernels C/D keep.
        keep = (gi == 0) | (gi >= nx - 1)
        out_ref[:] = jnp.where(keep, ext[1:-1, :], new)

    if hi_start is None:
        write_masked()
        return
    # Interior fast path — same static-band-range reasoning as kernel C
    # (_mask_hi_start with t=0: band i holds a boundary/pad row only for
    # i == 0 or i >= hi_start).
    needs_mask = (i == 0) | (i >= hi_start)
    pl.when(needs_mask)(write_masked)

    @pl.when(jnp.logical_not(needs_mask))
    def _():
        out_ref[:] = new


def plan_bands(nrows: int, ny: int, dtype=jnp.float32,
               target_bytes: int | None = None) -> tuple[int, int]:
    """Band plan for ``nrows`` rows of ``ny`` cells: (bm, padded_nrows).

    ``bm`` is the band height; rows pad up to ``padded_nrows`` (a bm
    multiple) with inert out-of-domain rows so divisor-poor row counts
    (prime/odd nx, or a shard's nx+2T extended block) keep a full-depth
    band instead of collapsing to single-row programs — the same
    pad-to-multiple answer the sharded path gives uneven decompositions
    (parallel/sharded.padded_global_shape). bm is 8-aligned (the Mosaic
    sublane rule: block dims must divide (8, 128) or equal the array's)
    unless the whole array is one band.

    The byte target shrinks for very wide grids: the kernel's VMEM
    working set is several band-sized buffers plus per-step temporaries,
    all proportional to the row size. Empirical v5e envelope (round 3):
    2 MB bands compile and run through ny=8192 (bm=64, T=8 estimates
    12.8 MB — measured 191 Gcells/s vs 143 with 1 MB bands); beyond
    32 KB rows the estimate would cross the hard limit, so the target
    halves there. Both targets scale with the detected per-core VMEM
    (budget/4 and budget/8; the v5e's 8 MB budget reproduces the
    measured envelope exactly), so bigger-VMEM parts get proportionally
    deeper bands.
    """
    row_bytes = ny * jnp.dtype(dtype).itemsize
    if target_bytes is None:
        budget = vmem_budget_bytes()
        target_bytes = max(row_bytes,
                           budget // (8 if row_bytes > 32 * 1024 else 4))
    cap = max(1, target_bytes // row_bytes)
    if cap >= nrows:
        return nrows, nrows          # whole array is a single band
    bm = max(8, (cap // 8) * 8)
    return bm, -(-nrows // bm) * bm


def _tuned_band_config(nrows: int, ny: int, dtype, tsteps=None,
                       allow_window: bool = True):
    """Tuned (route, bm, T) from the opt-in tuning db
    (``HEAT2D_TUNE_DB`` / ``tune.set_tuning_db``), or None — the ONE
    consultation point the band planners share. Pure host-side lookup,
    validated against the live resource model by ``tune.runtime``; with
    no db active it returns None without touching anything, so traced
    programs are byte-identical to a build without the tune subsystem
    (the jaxpr-pinned tests hold that line). ``allow_window=False``:
    the caller can only compile the legacy kernel (parity step form,
    _resolve_bands consumers), so a C2 answer degrades to route C
    BEFORE it is recorded — provenance must describe the program that
    actually compiles."""
    try:
        from heat2d_tpu.tune import runtime as _tune_runtime
    except ImportError:  # pragma: no cover - partial install
        return None
    return _tune_runtime.band_config(nrows, ny, dtype, tsteps,
                                     allow_window=allow_window)


def _resolve_bands(m: int, n: int, dtype, bm: int | None) -> tuple[int, int]:
    """(bm, m_pad) from an explicit bm (ceil m to its multiple), the
    opt-in tuning db, or the plan_bands policy — the one place the
    padding rule lives."""
    if bm is None:
        tuned = _tuned_band_config(m, n, dtype, allow_window=False)
        if tuned is None:
            return plan_bands(m, n, dtype)
        bm = tuned.bm
    return bm, -(-m // bm) * bm


def _check_band_vmem(bm: int, tsteps: int, ny: int, dtype,
                     extra_bytes: int = 0) -> None:
    """Fast-fail for configs whose band kernel cannot fit VMEM: without
    this the TPU compiler surfaces an opaque remote-compile HTTP 500 (or
    hangs for minutes) instead of an actionable message. ``extra_bytes``:
    VMEM-resident operands beyond the band working set (the fused shard
    kernel's full-height column strips)."""
    est = (5 * (bm + 2 * tsteps) * ny * jnp.dtype(dtype).itemsize
           + extra_bytes)
    limit = vmem_hard_limit_bytes()
    if est > limit:
        if VMEM_HARD_LIMIT_BYTES is not None:
            origin = VMEM_LIMIT_ORIGIN or "set by the --vmem-budget override"
        else:
            total, kind = _vmem_total()
            origin = (f"derived from the detected {kind} "
                      f"({total / 2**20:.0f} MB/core; override with "
                      f"--vmem-budget)")
        raise ConfigError(
            f"stencil band kernel needs ~{est / 2**20:.0f} MB of VMEM "
            f"(band of {bm} rows + {2 * tsteps} halo rows x {ny} cells), "
            f"over the {limit / 2**20:.0f} MB limit {origin}: rows this "
            f"wide cannot stream through a single chip's band kernel. "
            f"Shard the y dimension across devices (--mode dist2d/hybrid "
            f"--gridy N) or reduce --halo-depth")


def _on_tpu() -> bool:
    """True when kernels lower through Mosaic (pltpu available and not
    interpreter mode) — the one predicate _mem_spaces/_parallel_grid
    share."""
    return pltpu is not None and not _interpret()


def _mem_spaces():
    """(vmem kwargs, smem kwargs) for BlockSpecs — empty in interpreter
    mode, where pltpu memory spaces don't apply."""
    if _on_tpu():
        return (dict(memory_space=pltpu.VMEM),
                dict(memory_space=pltpu.SMEM))
    return {}, {}


def _compiler_params_cls():
    """The CompilerParams class across jax versions (older jax names it
    TPUCompilerParams), or None on very old pallas — the ONE lookup the
    parallel-grid marking and the C2 sequential-grid route share."""
    return (getattr(pltpu, "CompilerParams", None)
            or getattr(pltpu, "TPUCompilerParams", None))


def _parallel_grid(ndims: int):
    """compiler_params marking every grid dimension parallel — band (and
    member) programs within one sweep are independent: each reads only
    its own block plus pre-gathered strip operands and writes only its
    own block, so Mosaic may pipeline them freely. Measured +6-9% on the
    4096^2 band kernel (interleaved A/B vs the default 'arbitrary').
    Empty off-TPU or when neither CompilerParams spelling exists."""
    if not _on_tpu():
        return {}
    params = _compiler_params_cls()
    if params is None:  # pragma: no cover - very old pallas
        return {}
    return dict(compiler_params=params(
        dimension_semantics=("parallel",) * ndims))


def _row_strips(blocks, t, first, last):
    """(ups, dns) neighbor-row strip arrays for a band program grid:
    band i's up-strip is the previous block's t-row tail (``first`` for
    band 0) and its down-strip the next block's t-row head (``last``
    for the final band). ``blocks`` is (nblk, bm, n) or batched
    (b, nblk, bm, n); first/last carry the matching leading axes. The
    one place the band-neighbor gather lives — kernels B/C, the shard
    kernel D, and the batched ensemble sweep all assemble through it.
    """
    ax = blocks.ndim - 3
    bm = blocks.shape[-2]
    head = (slice(None),) * ax
    ups = jnp.concatenate(
        [first, blocks[head + (slice(None, -1),)][..., bm - t:, :]],
        axis=ax)
    dns = jnp.concatenate(
        [blocks[head + (slice(1, None),)][..., :t, :], last], axis=ax)
    return ups, dns


def _banded_pallas(kernel_body, u, bm, t):
    """Launch ``kernel_body`` over the row bands of ``u`` with t-deep
    neighbor-row strips (zeros past the array edges) — the shared
    machinery of kernels B and C.

    ``u``'s row count must already be a bm multiple (callers pad via
    plan_bands). Band i's strips carry rows [i*bm - t, i*bm) and
    [(i+1)*bm, (i+1)*bm + t), riding as (1, t, n) blocks: Mosaic requires
    the last two block dims to divide (8, 128) or equal the array dims.

    ``u`` aliases the output: each program reads only its OWN (bm, n)
    block of ``u`` (the neighbor rows ride in via the strip operands,
    gathered before the call), so in-place is race-free. Without the
    alias, XLA keeps the step loop's carry in its alternate memory
    space and inserts a full-grid HBM copy to satisfy the kernel's
    default-space operand every sweep — measured 10% of device time at
    4096x4096 (profile: copy.11, 0.10 ms per 8-step sweep).
    """
    m, n = u.shape
    nblk = m // bm
    zeros = jnp.zeros((1, t, n), u.dtype)
    ups, dns = _row_strips(u.reshape(nblk, bm, n), t, zeros, zeros)

    mspace, _ = _mem_spaces()
    grid_spec = pl.GridSpec(
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((1, t, n), lambda i: (i, 0, 0), **mspace),
            pl.BlockSpec((bm, n), lambda i: (i, 0), **mspace),
            pl.BlockSpec((1, t, n), lambda i: (i, 0, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0), **mspace),
    )
    return pl.pallas_call(
        kernel_body,
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        input_output_aliases={1: 0},
        **_parallel_grid(1))(ups, u, dns)


def band_step(u, cx: float, cy: float, bm: int | None = None,
              domain_rows: int | None = None, step=_step_value):
    """One time step of an HBM-resident grid via a row-band program grid.

    Rows pad to a bm multiple (plan_bands); pad rows read garbage but the
    kept row nx-1 firewalls it from the domain, and the pad is sliced off
    before returning. ``domain_rows``: true domain row count when ``u``
    already carries pad rows (band_chunk pads once outside its loop).
    """
    m, ny = u.shape
    nx = m if domain_rows is None else domain_rows
    bm, m_pad = _resolve_bands(m, ny, u.dtype, bm)
    _check_band_vmem(bm, 0, ny, u.dtype)
    if m_pad > m:
        u = jnp.pad(u, ((0, m_pad - m), (0, 0)))
    hi_start = _mask_hi_start(nx, bm, 0)
    out = _banded_pallas(
        functools.partial(_band_kernel, bm=bm, nx=nx, ny=ny, cx=cx, cy=cy,
                          step=step,
                          hi_start=hi_start if hi_start > 1 else None),
        u, bm, 1)
    return out[:m] if m_pad > m else out


# --------------------------------------------------------------------- #
# Kernel C: temporally-blocked band multi-step
# --------------------------------------------------------------------- #
#
# Kernel B is HBM-bound: every time step re-reads and re-writes the whole
# grid (2 x grid bytes/step). Temporal blocking amortizes that: each band
# carries a T-row halo strip on each side and advances T steps in VMEM per
# HBM sweep — traffic per step drops ~T x (plus a 2T/bm read overhead).
# Correctness of the halo depth: after s in-VMEM steps the outermost s rows
# of the extended band are stale, so the center bm rows are exact for
# s <= T. Stale data can never cross a *global* boundary row because the
# clamp mask is applied every internal step: row 0 / row nx-1 never update
# (the CUDA guard, grad1612_cuda_heat.cu:58), so garbage in the
# out-of-domain strip rows of edge bands is firewalled at the boundary.

def _mask_hi_start(nx: int, bm: int, tsteps: int) -> int:
    """First band index whose extended rows reach the high boundary:
    band i's ext covers global rows [i*bm - t, (i+1)*bm + t), so it
    contains a clamped/pad row (gi >= nx-1) iff (i+1)*bm + t - 1 >= nx-1,
    i.e. i >= (nx - t) / bm - 1. Bands below this (and above 0) carry an
    all-false keep mask — the static fact behind the interior fast path.
    """
    return max(0, -(-(nx - tsteps) // bm) - 1)


def _band_multi_kernel(up_ref, u_ref, dn_ref, out_ref, *,
                       bm, tsteps, nx, ny, cx, cy, step, hi_start=None):
    i = pl.program_id(0)
    ext = jnp.concatenate([up_ref[0], u_ref[:], dn_ref[0]], axis=0)
    # Global row ids of ext rows; <=0 also covers out-of-domain strip rows.
    gi = (i * bm - tsteps
          + lax.broadcasted_iota(jnp.int32, (bm + 2 * tsteps, 1), 0))
    keep = (gi <= 0) | (gi >= nx - 1)

    def masked(v):
        return jnp.where(keep, v, step(v, cx, cy))

    if hi_start is None:
        # No interior band exists — one uniform masked body.
        out_ref[:] = _unrolled_steps(tsteps, masked, ext)[tsteps:-tsteps]
        return

    # Interior fast path: bands in (0, hi_start) have an all-false keep
    # mask (no boundary or pad row in their ext block — _mask_hi_start),
    # so the per-cell select every step is pure overhead there. The
    # boundary select is 1 of the step's ~7 effective VPU ops/cell;
    # dropping it for the (nblk - 2ish) interior bands bought +9% at
    # 4096^2 (measured round 4). pl.when lowers to real control flow, so
    # only one body executes per program.
    needs_mask = (i == 0) | (i >= hi_start)

    @pl.when(needs_mask)
    def _():
        out_ref[:] = _unrolled_steps(tsteps, masked, ext)[tsteps:-tsteps]

    @pl.when(jnp.logical_not(needs_mask))
    def _():
        out_ref[:] = _unrolled_steps(
            tsteps, lambda v: step(v, cx, cy), ext)[tsteps:-tsteps]


def band_multi_step(u, tsteps: int, cx: float, cy: float,
                    bm: int | None = None,
                    domain_rows: int | None = None, step=_step_value):
    """Advance ``tsteps`` time steps in one sweep of row-band programs.

    Rows pad to a bm multiple (plan_bands); pad rows sit past gi >= nx-1
    so the keep mask holds them at zero — they never corrupt the domain
    and slice off before returning. ``domain_rows``: true domain row
    count when ``u`` already carries pad rows.
    """
    m, ny = u.shape
    nx = m if domain_rows is None else domain_rows
    bm, m_pad = _resolve_bands(m, ny, u.dtype, bm)
    if tsteps < 1 or bm <= 2 * tsteps:
        # Not enough band depth to amortize — fall back to stepwise.
        out = u
        for _ in range(tsteps):
            out = band_step(out, cx, cy, bm=bm, domain_rows=domain_rows,
                            step=step)
        return out
    _check_band_vmem(bm, tsteps, ny, u.dtype)
    if m_pad > m:
        u = jnp.pad(u, ((0, m_pad - m), (0, 0)))
    # hi_start only when an interior (mask-free) band exists; otherwise
    # the uniform masked body avoids compiling a dead second branch.
    # ALSO require a full unroll group: with a ROLLED remainder loop
    # (tsteps % _STEP_UNROLL != 0) the two pl.when bodies each carry the
    # loop's VMEM stack and Mosaic allocates BOTH — measured 17.3 MB
    # scoped (over the 16 MB core) for bm=128/T=4 at 16 KB rows, where
    # the same shape at T=8 (one inlined group, no rolled loop) fits.
    # Remainder sweeps are a once-per-chunk tail; the fast path's win is
    # irrelevant there anyway.
    hi_start = (_mask_hi_start(nx, bm, tsteps)
                if tsteps % _STEP_UNROLL == 0 else 0)
    out = _banded_pallas(
        functools.partial(_band_multi_kernel, bm=bm, tsteps=tsteps,
                          nx=nx, ny=ny, cx=cx, cy=cy, step=step,
                          hi_start=hi_start if hi_start > 1 else None),
        u, bm, tsteps)
    return out[:m] if m_pad > m else out


#: Default temporal depth for HBM-resident grids. Bounded by VMEM (the
#: band needs bm > 2T rows) and by diminishing returns once traffic per
#: step is ~grid_bytes/T; 8 cuts HBM traffic ~8x.
DEFAULT_TSTEPS = 8


class BandPlan(NamedTuple):
    """The gathered-strip band schedule for one (grid, halo width):
    band height, padded row count, resolved temporal depth, and the
    per-sweep ghost-row depth ``halo_rows = halo_width * tsteps`` the
    strips actually ship. ONE place this geometry lives — the heat5
    and family-generic band runners consume it, and the IR verifier
    (analysis/ir.py) re-derives the expected strip depth from it when
    checking a traced band program's pallas_call operand shapes."""

    bm: int
    m_pad: int
    tsteps: int
    halo_width: int

    @property
    def halo_rows(self) -> int:
        return self.halo_width * self.tsteps


def band_plan(m: int, n: int, dtype, halo_width: int = 1,
              tsteps: int | None = None) -> BandPlan:
    """Resolve the gathered-strip band schedule: band height from the
    tuning db / planner (``_resolve_bands``), then the shallow-band
    reduction — the per-sweep halo depth ``w*T`` must stay below the
    band height, so shallow bands reduce the sweep depth to
    ``(bm-1) // (2w)`` — then the VMEM fast-fail at the resolved
    depth."""
    t = DEFAULT_TSTEPS if tsteps is None else tsteps
    bm, m_pad = _resolve_bands(m, n, dtype, None)
    if bm <= 2 * halo_width * t:
        t = max(1, (bm - 1) // (2 * halo_width))
    _check_band_vmem(bm, halo_width * t, n, dtype)
    return BandPlan(bm, m_pad, t, halo_width)


# --------------------------------------------------------------------- #
# Kernel C2: gather-free band sweeps (overlap window + scratch relay)
# --------------------------------------------------------------------- #
#
# Kernel C re-gathers the (nblk, T, ny) neighbor-row strips between every
# sweep — a separate XLA copy op (~2x 2T/bm of the grid's bytes per sweep)
# that cannot overlap the kernel. C2 eliminates the gather entirely
# (measured 187.5k -> 216-223k Mcells/s at 4096^2, bm 128->160):
#
# - The grid runs SEQUENTIALLY (dimension_semantics 'arbitrary'), which
#   turns program order into a dataflow edge:
# - DOWN-strips ride in the same operand via a row-overlapping pl.Element
#   window (bm+T rows starting at i*bm): the extra T rows are block i+1's
#   head, still holding OLD values when program i's window is fetched —
#   in-flight writes always trail the read frontier by >= bm - T rows, so
#   the in-place alias stays race-free with any pipeline lookahead.
# - UP-strips flow through a persistent (T, ny) VMEM scratch: program i
#   stashes its ORIGINAL tail rows before its output write; program i+1
#   reads the stash. Program 0 reads uninitialized scratch — those ext
#   rows sit at gi <= 0, where the keep mask firewalls any garbage
#   (including NaNs) exactly like out-of-domain pad rows.
#
# Mosaic constraints gate the route (window_band_viable): element starts
# must be sublane-aligned (bm % 8), window rows too ((bm+T) % 8 => T % 8),
# window width lane-aligned (ny % 128), and pl.Element has no interpreter
# support worth relying on — off-TPU falls back to kernel C (the TPU smoke
# runner pins C2 bitwise-equal to C on hardware).

def window_band_viable(ny: int, bm: int, tsteps: int) -> bool:
    return (_on_tpu() and _compiler_params_cls() is not None
            and ny % 128 == 0 and bm % 8 == 0
            and tsteps % 8 == 0 and bm > 2 * tsteps)


#: Measured C2 compile envelope on the 16 MB-VMEM v5e (round-4 probes):
#: max VIABLE ext rows (bm + 2T) per row width — 176 @ 16 KB rows,
#: 336 @ 8 KB, 64 @ 32 KB; the next probed step up (184 / 352 / 72
#: ext rows) OOMs the compiler's scoped VMEM (72 rows @ 32 KB need
#: 16.76 MB; full frontier in benchmarks/results/tune_bands.md). The envelope
#: does NOT follow a single bytes cap across widths (2.88 MB windows
#: compile at 16 KB rows but fail at 8 KB; 2 MB fails at 32 KB), hence
#: a probed table, not a formula. bm at these points is also the
#: measured perf optimum: 152 -> 223k Mcells/s at 4096^2, 320 -> 237k
#: at 2560x2048, 48 -> 204k at 8192^2.
_WINDOW_EXT_ROWS = {32 * 1024: 64, 16 * 1024: 176, 8 * 1024: 336}

#: Ext-row cap for row widths the table doesn't cover: 640 rows is the
#: largest window VERIFIED to compile off-table (bm=624 at 4 KB rows —
#: the round-4 chip sweep ran 1280x1024 through it before the pad-aware
#: scan widened); combined with the byte caps below it keeps every
#: unprobed width at or under a verified point instead of extrapolating
#: (the 2.5 MB byte cap alone admitted 80 ext rows at 32 KB — 16.76 MB
#: scoped, compile OOM).
_WINDOW_EXT_ROWS_UNPROBED_CAP = 640


#: Kinds the _WINDOW_EXT_ROWS envelope was actually MEASURED on (the
#: assumed-16 MB kinds in _KNOWN_VMEM_TOTAL_BYTES are not listed: their
#: true break points are unprobed, so an explicit --vmem-budget raise is
#: honored there as the documented escape hatch).
_PROBED_VMEM_KINDS = ("TPU v5 lite", "TPU v5e")


def _probed_table_ext_rows(table: dict, row_bytes: int) -> int | None:
    """Probed-table lookup with the shared device/override gating.

    On a kind a table was actually measured on, the entry binds
    regardless of any --vmem-budget override — the override changes the
    plan budget, not the physical chip, so neither a raise nor a lower
    may admit shapes past the measured compile break points (advisor r4
    + review r5). On unprobed kinds an explicit override is the
    documented escape hatch, so tables only apply un-overridden (where
    the 16 MB fallback total matches the probed device — the CPU test
    harness relies on that)."""
    total, kind = _vmem_total()
    if total != 16 * 1024 * 1024:
        return None
    if VMEM_BUDGET_BYTES is None or kind in _PROBED_VMEM_KINDS:
        return table.get(row_bytes)
    return None


def _probed_ext_rows(row_bytes: int) -> int | None:
    """Probed max ext rows for this row width, or None when the attached
    device is not a 16 MB-VMEM kind or the width is unprobed — the ONE
    lookup the C2/D2 planners and the explicit-bm fast-fail share (a
    site updating the table must not be able to desynchronize them)."""
    return _probed_table_ext_rows(_WINDOW_EXT_ROWS, row_bytes)


def _window_ext_rows(row_bytes: int, tsteps: int) -> int:
    """Max ext rows for a window sweep at this row width: the probed
    table when it applies, else a conservative byte cap (2.5 MB at the
    v5e budget) bounded by the largest VERIFIED off-table window
    (_WINDOW_EXT_ROWS_UNPROBED_CAP rows) and, for rows wider than any
    probed point, by the widest probed point's byte allowance — the
    envelope SHRINKS with width (2.63 MB ok at 8 KB rows, 2 MB is the
    break at 32 KB), so extrapolating the byte cap upward OOMs (the
    8192^2 compile failure this helper fixes)."""
    ext = _probed_ext_rows(row_bytes)
    total, kind = _vmem_total()
    budget = vmem_budget_bytes()
    if kind in _PROBED_VMEM_KINDS:
        # A raised override cannot enlarge the physical chip: off-table
        # widths must not scale their byte cap past the chip's real
        # budget (review r5 — a 24 KB-row plan under --vmem-budget 32M
        # would otherwise double the measured break region). A lowered
        # override still tightens below.
        budget = min(budget, total // 2)
    if ext is not None and budget >= total // 2:
        return ext
    cap_bytes = budget * 5 // 16
    if row_bytes > 16 * 1024:
        # At or beyond the widest probed points the break sits at
        # ~2-2.25 MB (64 ext rows x 32 KB), below the 2.5 MB narrow-row
        # cap — hold anything wider than the last generous probe point
        # (16 KB: 2.75 MB ok) to the 32 KB point's byte budget. ">"
        # with 16 KB, not 32: exactly-32 KB rows land here whenever the
        # table is bypassed (budget override), and the 16-32 KB gap is
        # unprobed.
        cap_bytes = min(cap_bytes, budget // 4)
    cap = max(8 + 2 * tsteps,
              min(cap_bytes // row_bytes, _WINDOW_EXT_ROWS_UNPROBED_CAP))
    # A lowered budget tightens probed widths too (min with the table,
    # which still fast-fail-binds above).
    return min(ext, cap) if ext is not None else cap


def _pad_aware_bm(nrows: int, bm_max: int, tsteps: int) -> int:
    """Pad-aware band-height refinement: minimize total ext rows swept,
    ceil(nrows/bm) * (bm + 2T) — a band height dividing the row count
    more evenly skips recomputing pad rows (4096 rows: bm=152 pads 8
    rows -> 223.1k Mcells/s vs bm=160 padding 64 -> 221.3k measured).
    The scan covers the WHOLE candidate range: narrow rows give a
    deep bm_max whose divisor-poor pad can be huge (1280 rows at 4 KB:
    bm_max=624 pads 592 rows -> 154k Mcells/s, while bm=320 pads zero
    -> 234k measured via the D2 divisor rule in round 4). Ties prefer
    the taller band (fewer programs)."""
    def cost(b):
        return (-(-nrows // b)) * (b + 2 * tsteps)

    env = bm_max                   # the ext envelope as handed in
    if bm_max >= nrows:
        bm = max(8, nrows // 8 * 8)
        if nrows % bm == 0:
            return bm              # exact single band, zero pad
        bm_max = bm                # else scan: the rounded-DOWN single
        #                            band pads nearly a whole band
    bm = bm_max
    # Range stop 2T + 8 keeps every candidate > 2T (the window-viability
    # floor) without a redundant in-loop guard (advisor r4).
    for b in range(bm_max, 2 * tsteps + 8, -8):
        if cost(b) < cost(bm):
            bm = b
    # Also weigh the single TALL band ceil(nrows/8)*8 when it fits the
    # ext envelope: one (tall + 2T)-row sweep can beat every multi-band
    # candidate (e.g. nrows=100, T=8: bm=104 sweeps 120 ext rows vs
    # bm=96's 2x112), and the scan above tops out at the rounded-DOWN
    # height so it never sees it (advisor r5). <=: on a cost tie the
    # taller band wins (fewer programs), matching the scan's preference.
    tall = -(-nrows // 8) * 8
    if tall != bm and 2 * tsteps < tall <= env and cost(tall) <= cost(bm):
        bm = tall
    return bm


def plan_from_ext(nrows: int, ext: int, tsteps: int) -> tuple[int, int]:
    """(bm, m_pad) from an ext-row envelope: the pad-aware band-height
    scan under ``ext`` plus the ceil-pad — the ONE planner tail every
    window-family planner (C2/C3/D2/ensemble) derives its bands
    through, so a fix to the alignment or floor rule lands everywhere
    at once (review r5)."""
    bm = _pad_aware_bm(nrows, max(8, (ext - 2 * tsteps) // 8 * 8), tsteps)
    return bm, -(-nrows // bm) * bm


def plan_window_band(nrows: int, ny: int, tsteps: int,
                     dtype=jnp.float32) -> tuple[int, int]:
    """(bm, m_pad) for the C2 route: probed envelope for the widths
    measured on the default-budget v5e; elsewhere the conservative
    _window_ext_rows bound (byte cap tightened beyond the probed widths
    plus a verified ext-row ceiling — the bare 2.5 MB cap compile-OOMs
    at 32 KB rows)."""
    ext = _window_ext_rows(ny * jnp.dtype(dtype).itemsize, tsteps)
    return plan_from_ext(nrows, ext, tsteps)


def _window_steps(n, one, v):
    """``n`` steps for the WINDOW-kernel family: inlined when n is under
    a full unroll group — a rolled short loop loses the cross-step
    unroll win (measured as the whole sweep slowing ~30%), and for
    n <= _STEP_UNROLL the inline stack cannot exceed the 8-step group
    body the C2 compile envelope was probed with. The non-window band
    kernels keep _unrolled_steps' always-rolled remainder: their widest
    user (the 8192-wide shard kernel) OOM'd Mosaic's stack on a 2-step
    inline."""
    if n < _STEP_UNROLL:
        for _ in range(n):
            v = one(v)
        return v
    return _unrolled_steps(n, one, v)


def _split_window_refs(has_w, has_e, refs):
    """(w_ref, e_ref, rest) from a window kernel's positional refs —
    the ONE unpack the C2/C3 sweep and resid kernels share."""
    w_ref = refs[0] if has_w else None
    e_ref = refs[1 if has_w else 0] if has_e else None
    return w_ref, e_ref, refs[has_w + has_e:]


def _concat_halo_cols(ext, w_ref, e_ref):
    """Concatenate the optional E/W halo-column windows onto a band's
    row-extended block, and the kept-center column slice. Halo columns
    ride in whole (their top/corner rows come from the strip windows'
    extended-row coverage, not the scratch relay)."""
    has_w, has_e = w_ref is not None, e_ref is not None
    if has_w or has_e:
        ext = jnp.concatenate(
            ([w_ref[0]] if has_w else []) + [ext]
            + ([e_ref[0]] if has_e else []), axis=1)
    t = w_ref.shape[-1] if has_w else (e_ref.shape[-1] if has_e else 0)
    return ext, slice(t if has_w else None, -t if has_e else None)


def _band_window_kernel(has_w, has_e, u_ref, *refs, bm, tsteps, nsub,
                        nx, cx, cy, step, hi_start):
    """C2/C3 window-sweep kernel. ``has_w``/``has_e``: optional per-band
    column-strip window operands (the C3 panel route — a panel's E/W
    halo columns from its neighbor panels, pre-windowed per band exactly
    like the shard kernels' _strip_windows operands). The keep mask
    stays ROW-only: edge panels extend toward the interior only, so the
    step form's kept first/last columns ARE the global y boundary there,
    and interior panels' outermost columns are discarded halo — the
    interior fast path survives panelization unchanged."""
    w_ref, e_ref, (out_ref, tail) = _split_window_refs(has_w, has_e, refs)
    i = pl.program_id(0)
    t = tsteps
    up = tail[:]                   # prev band's original tail (garbage @ i=0)
    tail[:] = u_ref[bm - t:bm, :]  # stash own original tail for band i+1
    ext = jnp.concatenate([up, u_ref[:]], axis=0)     # (bm + 2t, nyp)
    ext, cs = _concat_halo_cols(ext, w_ref, e_ref)
    gi = (i * bm - t + lax.broadcasted_iota(jnp.int32, (bm + 2 * t, 1), 0))
    keep = (gi <= 0) | (gi >= nx - 1)

    def masked(v):
        return jnp.where(keep, v, step(v, cx, cy))

    if hi_start is None:
        out_ref[:] = _window_steps(nsub, masked, ext)[t:-t, cs]
        return
    needs_mask = (i == 0) | (i >= hi_start)

    @pl.when(needs_mask)
    def _():
        out_ref[:] = _window_steps(nsub, masked, ext)[t:-t, cs]

    @pl.when(jnp.logical_not(needs_mask))
    def _():
        out_ref[:] = _window_steps(
            nsub, lambda v: step(v, cx, cy), ext)[t:-t, cs]


def _window_operands(u, wwin, ewin, bm, t, mspace):
    """(in_specs, args) for a C2/C3 window sweep: the row-overlapping
    element window over the carry plus the optional per-band E/W
    column-strip windows — the ONE operand-assembly the plain and resid
    sweeps share."""
    in_specs = [pl.BlockSpec((pl.Element(bm + t), pl.Element(u.shape[1])),
                             lambda i: (i * bm, 0), **mspace)]
    args = [u]
    strip_spec = pl.BlockSpec((1, bm + 2 * t, t), lambda i: (i, 0, 0),
                              **mspace)
    for win in (wwin, ewin):
        if win is not None:
            in_specs.append(strip_spec)
            args.append(win)
    return in_specs, args


def _band_window_sweep(u, tsteps, cx, cy, bm, nx, step, nsub=None,
                       wwin=None, ewin=None):
    """One sweep over ``u`` of shape (m_pad + T, nyp); the last T rows
    are inert overrun pad for the last band's element window. ``nsub``:
    steps to advance this sweep (<= tsteps; default tsteps) — the
    window/relay geometry stays T-deep, only fewer steps run, so the
    kept centers (T rows in, stale depth nsub <= T) remain exact. This
    is how ``n % T`` remainders stay on the window route instead of
    dropping to a legacy gathered sweep (which cost ~2x per step —
    rolled loop + re-gather — and showed up directly in the fused
    convergence overhead).

    ``wwin``/``ewin``: optional (nblk, bm+2T, T) per-band column-strip
    windows (the C3 panel route)."""
    mt, nyp = u.shape
    t = tsteps
    nblk = (mt - t) // bm
    # Partial sweeps (nsub < T) run the uniform masked body: their steps
    # INLINE (_window_steps), and two pl.when bodies of inlined steps
    # would double the Mosaic VMEM stack past the envelope probed with
    # one 8-step body — the same dual-body OOM band_multi_step gates.
    # They are once-per-chunk tails; the fast path is irrelevant there.
    hi_start = (_mask_hi_start(nx, bm, t)
                if nsub is None or nsub == tsteps else 0)
    mspace, _ = _mem_spaces()
    params = _compiler_params_cls()   # non-None: window_band_viable gated
    in_specs, args = _window_operands(u, wwin, ewin, bm, t, mspace)
    return pl.pallas_call(
        functools.partial(_band_window_kernel, wwin is not None,
                          ewin is not None, bm=bm, tsteps=t,
                          nsub=tsteps if nsub is None else nsub, nx=nx,
                          cx=cx, cy=cy, step=step,
                          hi_start=hi_start if hi_start > 1 else None),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        grid=(nblk,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, nyp), lambda i: (i, 0), **mspace),
        scratch_shapes=[pltpu.VMEM((t, nyp), u.dtype)],
        input_output_aliases={0: 0},
        compiler_params=params(dimension_semantics=("arbitrary",)),
    )(*args)


def _band_window_resid_kernel(has_w, has_e, u_ref, *refs, bm, tsteps,
                              nsub, nx, cx, cy, step):
    """C2/C3 window sweep that ALSO emits each band's partial residual
    Σ(Δu)² of the sweep's LAST step pair (rows of the band's kept
    center; boundary/pad rows are keep-masked so their delta is 0).
    One uniform masked body — the dual-body fast path doubles Mosaic's
    VMEM stack (the round-4 remainder-sweep OOM) and this kernel runs
    once per INTERVAL, where the select cost is irrelevant."""
    w_ref, e_ref, (out_ref, r_ref, tail) = _split_window_refs(
        has_w, has_e, refs)
    i = pl.program_id(0)
    t = tsteps
    up = tail[:]
    tail[:] = u_ref[bm - t:bm, :]
    ext = jnp.concatenate([up, u_ref[:]], axis=0)
    ext, cs = _concat_halo_cols(ext, w_ref, e_ref)
    gi = (i * bm - t + lax.broadcasted_iota(jnp.int32, (bm + 2 * t, 1), 0))
    keep = (gi <= 0) | (gi >= nx - 1)

    def masked(v):
        return jnp.where(keep, v, step(v, cx, cy))

    # All nsub steps INLINED as one group (nsub <= t == _STEP_UNROLL by
    # the route's gate): `_unrolled_steps(nsub-1)` would take its
    # rolled-loop path — measured as the whole sweep losing the
    # cross-step unroll win and conv overhead REGRESSING at 2560x2048
    # (18.5% -> 35.1%). Inlining matches kernel C2's own group body;
    # only `prev` adds a live array. ``nsub`` < t is the round-5
    # chunk-tail schedule: the resid sweep advances only the chunk's
    # REMAINDER depth so every other sweep stays a full fast one.
    v = ext
    for _ in range(nsub - 1):
        v = masked(v)
    prev = v
    last = masked(v)
    out_ref[:] = last[t:-t, cs]
    d = last[t:-t, cs] - prev[t:-t, cs]
    # Shaped (1, 1, 1) store: Mosaic has no scalar stores to VMEM.
    r_ref[...] = jnp.sum(d * d).reshape(1, 1, 1)


def _window_resid_sweep(u, tsteps, cx, cy, bm, nx, step,
                        wwin=None, ewin=None, nsub=None):
    """One C2R/C3R resid sweep over the (m_pad + T, nyp) padded layout,
    advancing ``nsub`` (<= T; default T) steps: returns (u_new,
    residual) with the residual summed from the per-band partials
    (summation order differs from residual_sq's full-array sum at
    f32-ulp level — same deviation class as the FMA step form this
    route is gated to)."""
    mt, nyp = u.shape
    t = tsteps
    nblk = (mt - t) // bm
    mspace, _ = _mem_spaces()
    params = _compiler_params_cls()
    in_specs, args = _window_operands(u, wwin, ewin, bm, t, mspace)
    out, parts = pl.pallas_call(
        functools.partial(_band_window_resid_kernel, wwin is not None,
                          ewin is not None, bm=bm, tsteps=t,
                          nsub=t if nsub is None else nsub,
                          nx=nx, cx=cx, cy=cy, step=step),
        # Partials ride as (nblk, 1, 1) with (1, 1, 1) blocks — the last
        # two block dims must equal the array's (a (1, 1) block over
        # (nblk, 1) breaks the Mosaic block rule for nblk > 1, the same
        # real-TPU-only failure the ensemble scalar blocks hit).
        out_shape=[jax.ShapeDtypeStruct(u.shape, u.dtype),
                   jax.ShapeDtypeStruct((nblk, 1, 1), jnp.float32)],
        grid=(nblk,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((bm, nyp), lambda i: (i, 0), **mspace),
                   pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0), **mspace)],
        scratch_shapes=[pltpu.VMEM((t, nyp), u.dtype)],
        input_output_aliases={0: 0},
        compiler_params=params(dimension_semantics=("arbitrary",)),
    )(*args)
    return out, jnp.sum(parts)


def _window_multi_padded(up, n, tsteps, cx, cy, bm, nx, step):
    """``n`` steps on the padded (m_pad + T, ny) sweep layout: full
    T-sweeps plus a partial-depth (nsub) remainder sweep — the ONE
    sweep-scheduling loop the C2 chunk and the persistent-carry fused
    convergence runner share."""
    nsweeps, rem = divmod(n, tsteps)
    if nsweeps:
        up = lax.fori_loop(
            0, nsweeps,
            lambda _, v: _band_window_sweep(v, tsteps, cx, cy, bm, nx,
                                            step),
            up, unroll=False)
    if rem:
        up = _band_window_sweep(up, tsteps, cx, cy, bm, nx, step,
                                nsub=rem)
    return up


def _window_chunk(u, n, cx, cy, tsteps, bm, step):
    """``n`` steps via gather-free window sweeps (kernel C2); the
    ``n % T`` remainder runs as a partial-depth window sweep (nsub) —
    same kernel, same layout, inlined short step loop."""
    nx, ny = u.shape
    _check_band_vmem(bm, tsteps, ny, u.dtype)
    # The probed envelope binds explicit bm too: past it the compile
    # dies in the opaque scoped-VMEM OOM the fast-fail exists to
    # prevent (the est-based check alone admits e.g. bm=328 at 8 KB
    # rows, 8 ext rows over the measured break).
    ext_cap = _probed_ext_rows(ny * jnp.dtype(u.dtype).itemsize)
    if ext_cap is not None and bm + 2 * tsteps > ext_cap:
        raise ConfigError(
            f"band window of {bm + 2 * tsteps} ext rows x {ny} cells is "
            f"over the probed {ext_cap}-row compile envelope for this "
            f"row width ({_vmem_total()[1]}): use bm <= "
            f"{(ext_cap - 2 * tsteps) // 8 * 8} or let plan_window_band "
            f"choose")
    m_pad = -(-nx // bm) * bm
    up = jnp.pad(u, ((0, m_pad - nx + tsteps), (0, 0)))
    return _window_multi_padded(up, n, tsteps, cx, cy, bm, nx,
                                step)[:nx]


def band_chunk(u, n: int, cx: float, cy: float,
               tsteps: int = DEFAULT_TSTEPS, bm: int | None = None,
               step=_step_value):
    """Advance ``n`` (static) steps: full T-sweeps plus a remainder sweep.

    Routes to the gather-free window kernel (C2) when its Mosaic
    constraints hold — on TPU, lane-aligned width, 8-aligned bm/T; an
    explicit ``bm`` is honored on whichever route it is viable for.
    Legacy route: divisor-poor row counts pad ONCE here for the whole
    loop (the padded shape is a fixed point under the keep-masked
    kernels), not per sweep.

    With a tuning db active (``HEAT2D_TUNE_DB``) and no explicit
    ``bm``, the measured best (bm, T, route) for this shape replaces
    the heuristic plan: route "C" pins the legacy kernel even where
    the window route is viable, route "C2" carries the tuned band
    height into the window planner. Absent/missing db: the static
    policy below, unchanged.
    """
    nx, ny = u.shape
    force_legacy = False
    if bm is None:
        tuned = _tuned_band_config(nx, ny, u.dtype, tsteps,
                                   allow_window=step is _step_value)
        if tuned is not None:
            bm, tsteps = tuned.bm, tuned.tsteps
            force_legacy = tuned.route == "C"
    bm_w = bm
    if (bm_w is None and _on_tpu() and ny % 128 == 0
            and tsteps % 8 == 0):
        bm_w, _ = plan_window_band(nx, ny, tsteps, u.dtype)
    # The C2 envelope table was probed with the FMA step form; the
    # literal (bitwise-parity) form carries more live temporaries and
    # OOMs at the same bm (measured: 18.1 MB vs <16 at bm=320, 8 KB
    # rows), so parity runs — correctness runs, not perf runs — keep
    # the legacy route.
    if (not force_legacy and step is _step_value and bm_w is not None
            and window_band_viable(ny, bm_w, tsteps)):
        return _window_chunk(u, n, cx, cy, tsteps, bm_w, step)
    bm, m_pad = _resolve_bands(nx, ny, u.dtype, bm)
    if m_pad > nx:
        u = jnp.pad(u, ((0, m_pad - nx), (0, 0)))
    nsweeps, rem = divmod(n, tsteps)
    if nsweeps:
        u = lax.fori_loop(
            0, nsweeps,
            lambda _, v: band_multi_step(v, tsteps, cx, cy, bm=bm,
                                         domain_rows=nx, step=step), u,
            unroll=False)
    if rem:
        u = band_multi_step(u, rem, cx, cy, bm=bm, domain_rows=nx,
                            step=step)
    return u[:nx] if m_pad > nx else u


# --------------------------------------------------------------------- #
# Kernel C3: column-panel window sweeps for very wide rows
# --------------------------------------------------------------------- #
#
# The C2 compile envelope SHRINKS with row width (176 ext rows at 16 KB
# rows, 336 at 8 KB, only 64 at 32 KB — tune_bands.md), so 8192-wide
# grids were stuck at bm=48 paying a 33% halo-recompute tax per sweep
# (203.5k Mcells/s vs the framework's 237.5k frontier at 8 KB rows;
# VERDICT r4 weak #1). C3 restores the deep-band envelope by walking the
# grid in P column PANELS of nyp = ny/P cells:
#
# - Each panel keeps its own (m_pad + T, nyp) C2 carry; its sweeps are
#   plain C2 window sweeps plus per-band E/W column-strip windows (the
#   shard kernel D's _strip_windows operands) holding the T halo columns
#   from the neighbor panels — gathered fresh each sweep from the
#   pre-sweep carries (T/nyp of the grid's bytes, ~0.4%; nothing like
#   the 2T/bm row-strip gather C2 exists to avoid).
# - Edge panels extend toward the interior ONLY: their outer ext column
#   is the global y boundary itself, which the step form keeps — so the
#   keep mask stays row-only and the interior mask-free fast path
#   survives panelization (a D2-style column mask would have disabled
#   it for every band of both edge panels at P=2).
# - Staleness: a panel's halo columns are exact at sweep start and
#   degrade one column per in-VMEM step; the kept center sits T columns
#   in — the same cone argument as the rows (and as kernel D's strips).
# - Bitwise: every cell's per-step arithmetic DAG is unchanged (same
#   step form, same keep semantics), so C3 output is BITWISE equal to
#   C2/C at any panel count — tpu_smoke pins this on hardware.

#: Measured C3 compile envelope on the v5e (round-5 probes, T=8): max
#: ext rows per PANEL row width WITH the two column-strip windows —
#: much tighter than C2's no-cols table (the strips cost ~50-90 ext
#: rows of compiler headroom, not the 8 rows D2's short-shard probe
#: suggested): bm=112 compiles at 16 KB panels, bm=120 does not;
#: bm=248 / bm=256 at 8 KB; bm=464 / bm=504 at 4 KB (all at 8192-row
#: grids — full frontier in benchmarks/results/tune_bands.md).
_PANEL_WINDOW_EXT_ROWS = {16 * 1024: 128, 8 * 1024: 264, 4 * 1024: 480}

#: Fallback headroom for panel widths the table doesn't cover: the
#: largest measured gap between the no-cols and with-cols envelopes
#: (4 KB rows: 640 -> 480).
_PANEL_COL_EXT_ALLOWANCE = 160


def _panel_ext_rows(row_bytes: int, tsteps: int) -> int:
    ext = _probed_table_ext_rows(_PANEL_WINDOW_EXT_ROWS, row_bytes)
    if ext is not None:
        return ext
    return max(8 + 2 * tsteps,
               _window_ext_rows(row_bytes, tsteps)
               - _PANEL_COL_EXT_ALLOWANCE)


def plan_panel_window(nrows: int, nyp: int, tsteps: int,
                      dtype=jnp.float32) -> tuple[int, int]:
    """(bm, m_pad) for a C3 panel of width ``nyp``: the pad-aware band
    scan under the panel (with-cols) envelope at the panel's row
    width."""
    ext = _panel_ext_rows(nyp * jnp.dtype(dtype).itemsize, tsteps)
    return plan_from_ext(nrows, ext, tsteps)


def plan_panels(nrows: int, ny: int, tsteps: int,
                dtype=jnp.float32) -> tuple[int, int | None]:
    """(P, bm) for the single-chip window route; P=1 means plain C2
    (bm=None: caller uses plan_window_band).

    MEASURED policy (tune_panels, 8192^2 + 4096^2 on the v5e): split
    only when the row width's own C2 envelope has collapsed — at 16 KB
    rows (4096^2) every P=2 config LOSES 3-7% to plain C2 (the per-sweep
    strip gathers and per-panel launches weigh 4x more at the smaller
    grid), while at 32 KB rows (8192^2) P=2 wins +7.5% same-run
    (201.3k vs 187.3k Mcells/s). The split lands panels at <= 16 KB
    rows — the last width with a generous envelope; smaller panels
    measured strictly worse at 8192^2 (P=4: 199.9k, P=8: 174.0k vs
    P=2: 201.3k — the deeper envelope of narrower panels doesn't make
    up the extra boundary columns and launches)."""
    if not (_on_tpu() and _compiler_params_cls() is not None):
        return 1, None
    itemsize = jnp.dtype(dtype).itemsize
    row_bytes = ny * itemsize
    if (ny % 128 or tsteps % 8 or tsteps < 8
            or row_bytes <= 16 * 1024):
        return 1, None
    pp = -(-row_bytes // (16 * 1024))     # smallest P reaching <= 16 KB
    if ny % pp or (ny // pp) % 128:
        return 1, None
    bm, _ = plan_panel_window(nrows, ny // pp, tsteps, dtype)
    if bm <= 2 * tsteps or bm % 8:
        return 1, None
    return pp, bm


def panel_route_viable(ny: int, panels: int, bm: int | None,
                       tsteps: int) -> bool:
    if panels < 2 or bm is None or ny % panels:
        return False
    return window_band_viable(ny // panels, bm, tsteps)


def _panel_split(u, panels: int, bm: int, tsteps: int):
    """(nx, ny) -> tuple of P (m_pad + T, nyp) panel carries (each the
    C2 padded sweep layout over its own columns)."""
    nx, ny = u.shape
    nyp = ny // panels
    m_pad = -(-nx // bm) * bm
    pad = ((0, m_pad - nx + tsteps), (0, 0))
    return tuple(jnp.pad(u[:, p * nyp:(p + 1) * nyp], pad)
                 for p in range(panels))


def _panel_join(carries, nx: int):
    return jnp.concatenate([c[:nx] for c in carries], axis=1)


def _panel_strip_windows(carries, bm: int, t: int):
    """Per-sweep cross-panel halo windows: panel p's west window from
    panel p-1's tail columns, east from panel p+1's head columns, as
    (nblk, bm+2T, T) per-band windows (_strip_windows on a full-height
    strip with T zero rows on top — rows above the domain are
    keep-masked like every other out-of-domain row, and rows below it
    are the carries' inert pad, 0 forever). Gathered from the PRE-sweep
    carries: every panel's new value depends only on old neighbor
    values, so sweep order between panels is immaterial."""
    mt = carries[0].shape[0]          # m_pad + T
    nblk = (mt - t) // bm
    z = jnp.zeros((t, t), carries[0].dtype)

    def windows(cols):
        return _strip_windows(jnp.concatenate([z, cols], axis=0),
                              nblk, bm, t)

    last = len(carries) - 1
    return [(windows(carries[p - 1][:, -t:]) if p else None,
             windows(carries[p + 1][:, :t]) if p < last else None)
            for p in range(len(carries))]


def _panel_sweep_all(carries, tsteps, cx, cy, bm, nx, step, nsub=None,
                     resid=False):
    """One window sweep of every panel (strips gathered first, from the
    pre-sweep carries). ``resid=True``: C3R — every panel's sweep is a
    resid sweep; returns (carries, Σ partials)."""
    wins = _panel_strip_windows(carries, bm, tsteps)
    if resid:
        outs, parts = [], []
        for c, (w, e) in zip(carries, wins):
            o, r = _window_resid_sweep(c, tsteps, cx, cy, bm, nx, step,
                                       wwin=w, ewin=e, nsub=nsub)
            outs.append(o)
            parts.append(r)
        return tuple(outs), sum(parts)
    return tuple(
        _band_window_sweep(c, tsteps, cx, cy, bm, nx, step, nsub=nsub,
                           wwin=w, ewin=e)
        for c, (w, e) in zip(carries, wins))


def _panel_multi(carries, n, tsteps, cx, cy, bm, nx, step):
    """``n`` steps on the panel carries: full T-sweeps plus a
    partial-depth remainder sweep — _window_multi_padded for the panel
    route."""
    nsweeps, rem = divmod(n, tsteps)
    if nsweeps:
        carries = lax.fori_loop(
            0, nsweeps,
            lambda _, cs: _panel_sweep_all(cs, tsteps, cx, cy, bm, nx,
                                           step),
            carries, unroll=False)
    if rem:
        carries = _panel_sweep_all(carries, tsteps, cx, cy, bm, nx, step,
                                   nsub=rem)
    return carries


def panel_chunk(u, n: int, cx: float, cy: float,
                tsteps: int = DEFAULT_TSTEPS, panels: int | None = None,
                bm: int | None = None, step=_step_value):
    """Advance ``n`` (static) steps via the C3 panel route. ``panels``/
    ``bm`` default to the plan_panels policy (which may choose P=1 —
    then this is exactly band_chunk's window route)."""
    nx, ny = u.shape
    if panels is None:
        panels, bm = plan_panels(nx, ny, tsteps, u.dtype)
    if panels < 2:
        return band_chunk(u, n, cx, cy, tsteps=tsteps, bm=bm, step=step)
    if ny % panels:
        raise ConfigError(
            f"panel count {panels} does not divide the {ny}-cell row "
            f"width — columns would be silently dropped")
    if bm is None or bm % 8 or bm <= 2 * tsteps:
        raise ConfigError(
            f"explicit panels={panels} needs an explicit 8-aligned "
            f"bm > {2 * tsteps}, got {bm} (or let plan_panels choose "
            f"both)")
    nyp = ny // panels
    strip_bytes = (2 * (bm + 2 * tsteps) * max(tsteps, 128)
                   * jnp.dtype(u.dtype).itemsize)
    _check_band_vmem(bm, tsteps, nyp + 2 * tsteps, u.dtype,
                     extra_bytes=strip_bytes)
    ext_cap = _probed_table_ext_rows(_PANEL_WINDOW_EXT_ROWS,
                                     nyp * jnp.dtype(u.dtype).itemsize)
    if ext_cap is not None and bm + 2 * tsteps > ext_cap:
        raise ConfigError(
            f"panel window of {bm + 2 * tsteps} ext rows x {nyp} cells "
            f"(+column strips) is over the probed {ext_cap}-row "
            f"with-cols envelope for this panel width "
            f"({_vmem_total()[1]}): use bm <= "
            f"{(ext_cap - 2 * tsteps) // 8 * 8} or let plan_panels "
            f"choose")
    carries = _panel_split(u, panels, bm, tsteps)
    carries = _panel_multi(carries, n, tsteps, cx, cy, bm, nx, step)
    return _panel_join(carries, nx)


# --------------------------------------------------------------------- #
# Engine integration
# --------------------------------------------------------------------- #

def make_single_chip_runner(config, tap=None):
    """Compiled ``u0 -> (u_final, steps_done)`` for mode='pallas'.

    Fixed-step runs on a VMEM-sized grid execute as ONE kernel invocation;
    convergence runs chunk INTERVAL steps per invocation so the residual
    check (implemented correctly, unlike the reference — SURVEY.md A.2)
    stays on-device between chunks. HBM-sized grids stream band-kernel
    steps under lax.fori/while exactly like the golden engine.

    ``config.bitwise_parity`` selects the literal reference step form
    (bitwise identical to serial mode) over the default FMA factoring —
    the same switch hybrid mode has.

    ``tap``: optional convergence-loop residual stream (engine._emit);
    None adds nothing to the traced program. The Pallas chunk launches
    carry ``phase('stencil_chunk')`` scope metadata so XProf and
    heat2d-tpu-prof attribute kernel time to the chunk phase.
    """
    cx, cy = config.cx, config.cy
    nx, ny = config.nxprob, config.nyprob
    resident = fits_vmem((nx, ny))
    form = (_step_value_literal if getattr(config, "bitwise_parity", False)
            else _step_value)
    # C3 panel route for very wide HBM grids (FMA form only — the panel
    # envelope, like C2's, was probed with it; parity runs keep the
    # legacy route via band_chunk).
    pP, pbm = ((1, None) if resident or form is not _step_value
               else plan_panels(nx, ny, DEFAULT_TSTEPS))
    use_panels = panel_route_viable(ny, pP, pbm, DEFAULT_TSTEPS)

    if resident:
        def step(u):
            return multi_step_vmem(u, 1, cx, cy, step=form)

        def chunk(u, n):  # n is a static Python int: baked into the kernel
            with phase("stencil_chunk"):
                return multi_step_vmem(u, n, cx, cy, step=form)
    elif use_panels:
        def step(u):
            # The tracked single step (unfused convergence only): the
            # route-agnostic gathered band step — bitwise-equal to an
            # nsub=1 panel sweep and far cheaper than the panel
            # split/strip/join machinery for one step.
            return band_step(u, cx, cy, step=form)

        def chunk(u, n):
            with phase("stencil_chunk"):
                return panel_chunk(u, n, cx, cy, panels=pP, bm=pbm,
                                   step=form)
    else:
        def step(u):
            return band_step(u, cx, cy, step=form)

        def chunk(u, n):  # temporally-blocked sweeps (~T x less HBM traffic)
            with phase("stencil_chunk"):
                return band_chunk(u, n, cx, cy, step=form)

    # Fused-residual convergence (C2R): on the streaming C2 route the
    # chunk's tracked step + residual reduction fold into the last
    # window sweep — the unfused pair cost ~78% over fixed-step at
    # 4096² (sweep_conv.md round 4). The carry stays in the PADDED
    # (m_pad + T, ny) sweep layout across the whole while loop (the D2
    # persistent-carry trick — re-padding per chunk cost ~10% of the
    # chunk at 4096²); extend/strip happen once per run. Any interval
    # >= 1 is viable since round 5's chunk-tail schedule (the resid
    # sweep's depth adapts to the chunk tail, d = n % T or T). Parity
    # runs (literal form) and resident grids keep the chunked loop.
    fused = None
    if (config.convergence and not resident and form is _step_value
            and _on_tpu() and ny % 128 == 0):
        tw = DEFAULT_TSTEPS
        if use_panels:
            # C3R: the panel carries ride the whole while loop (the
            # persistent-carry trick); each chunk's last sweep is a
            # resid sweep on every panel, partials summed across
            # bands AND panels.
            def multi_c3(cs, n):
                return _panel_multi(cs, n, tw, cx, cy, pbm, nx, form)

            def chunk_resid_c3(cs, n):
                # Chunk-tail resid schedule: the resid sweep advances
                # only the remainder depth so every other sweep is a
                # full fast one (round-5: cut conv overhead ~in half).
                d = n % tw or tw
                cs = multi_c3(cs, n - d)
                with phase("residual_reduction"):
                    return _panel_sweep_all(cs, tw, cx, cy, pbm, nx,
                                            form, nsub=d, resid=True)

            def fused(u):
                cs = _panel_split(u, pP, pbm, tw)
                cs, k = engine.run_convergence_fused(
                    chunk_resid_c3, multi_c3, cs,
                    config.steps, config.interval, config.sensitivity,
                    tap=tap)
                return _panel_join(cs, nx), k
        else:
            bm_w, m_pad_w = plan_window_band(nx, ny, DEFAULT_TSTEPS)
            if window_band_viable(ny, bm_w, DEFAULT_TSTEPS):
                def multi_p(up, n):
                    return _window_multi_padded(up, n, tw, cx, cy, bm_w,
                                                nx, form)

                def chunk_resid_p(up, n):
                    # Chunk-tail resid schedule (see chunk_resid_c3).
                    d = n % tw or tw
                    up = multi_p(up, n - d)
                    with phase("residual_reduction"):
                        return _window_resid_sweep(up, tw, cx, cy, bm_w,
                                                   nx, form, nsub=d)

                def fused(u):
                    up = jnp.pad(u, ((0, m_pad_w - nx + tw), (0, 0)))
                    up, k = engine.run_convergence_fused(
                        chunk_resid_p, multi_p, up,
                        config.steps, config.interval,
                        config.sensitivity, tap=tap)
                    return up[:nx], k

    def run(u):
        def residual(a, b):
            with phase("residual_reduction"):
                return residual_sq(a, b)
        if config.convergence:
            if fused is not None:
                return fused(u)
            return engine.run_convergence_chunked(
                chunk, step, residual, u,
                config.steps, config.interval, config.sensitivity,
                tap=tap)
        # Fixed-step: resident grids run as ONE kernel invocation;
        # HBM grids as temporally-blocked sweeps.
        u = chunk(u, config.steps)
        return u, jnp.asarray(config.steps, jnp.int32)

    return jax.jit(run)


# --------------------------------------------------------------------- #
# Kernel D: per-shard fused chunk kernels for mode='hybrid'
# --------------------------------------------------------------------- #
#
# The shard-local analogue of kernels A and C: inside shard_map, each
# device holds a (bm, bn) block plus the four t-deep halo strips from
# parallel.halo.exchange_halo_strips and must advance the block T steps.
# The round-2 design materialized the (bm+2T, bn+2T) extended block in
# HBM (two concatenates), streamed it through the kernel, then sliced the
# center back out — three full-block HBM round-trips per chunk on top of
# the kernel's own traffic, which held hybrid at 45% of the single-chip
# kernel's throughput (VERDICT r2 weak #1). These kernels fuse all of
# that: the strips ride in as separate operands, the extended block is
# assembled in VMEM, and only the exact center is ever written back.
# Routing by size is unchanged — whole block resident in VMEM when it
# fits, streamed in temporally-blocked row bands when it doesn't.
#
# Unlike kernels A-C, the keep mask here depends on the shard's mesh
# position (lax.axis_index — a *traced* value), so the global coordinates
# of the block's (0,0) ride in as an SMEM scalar operand.

def _shard_keep_mask(row0, col0, shape, nx, ny, row_shift=0, col_shift=0):
    """(gi<=0)|(gi>=nx-1)|(gj<=0)|(gj>=ny-1) over ``shape``: global
    boundary cells plus out-of-domain ghost/pad cells — the in-kernel
    twin of parallel.sharded._keep_mask. row0/col0 may be traced."""
    gi = (row0 + row_shift
          + lax.broadcasted_iota(jnp.int32, (shape[0], 1), 0))
    gj = (col0 + col_shift
          + lax.broadcasted_iota(jnp.int32, (1, shape[1]), 1))
    return (gi <= 0) | (gi >= nx - 1) | (gj <= 0) | (gj >= ny - 1)


def _shard_fused_vmem_kernel(s_ref, w_ref, e_ref, n_ref, u_ref, sth_ref,
                             out_ref, *, tsteps, nx, ny, cx, cy, step):
    t = tsteps
    vert = jnp.concatenate([n_ref[:], u_ref[:], sth_ref[:]], axis=0)
    ext = jnp.concatenate([w_ref[:], vert, e_ref[:]], axis=1)
    keep = _shard_keep_mask(s_ref[0], s_ref[1], ext.shape, nx, ny,
                            row_shift=-t, col_shift=-t)

    def one(v):
        return jnp.where(keep, v, step(v, cx, cy))

    ext = _unrolled_steps(tsteps, one, ext)
    out_ref[:] = ext[t:-t, t:-t]


def _shard_fused_band_kernel(s_ref, w_ref, e_ref, up_ref, u_ref, dn_ref,
                             out_ref, *, rb, tsteps, nx, ny, cx, cy, step):
    i = pl.program_id(0)
    t = tsteps
    vert = jnp.concatenate([up_ref[0], u_ref[:], dn_ref[0]], axis=0)
    # Column strips arrive pre-windowed per band (1, rb+2t, t) — riding
    # them whole would keep a full-height (m+2t, t) array VMEM-resident
    # in every program, and Mosaic lane-pads the t-wide minor dim to 128,
    # a 16x bloat that OOM'd VMEM at 8192-row shards (compiler: 18.8 MB
    # scoped for a 13 MB estimate).
    ext = jnp.concatenate([w_ref[0], vert, e_ref[0]], axis=1)
    keep = _shard_keep_mask(s_ref[0], s_ref[1], ext.shape, nx, ny,
                            row_shift=i * rb - t, col_shift=-t)

    def one(v):
        return jnp.where(keep, v, step(v, cx, cy))

    ext = _unrolled_steps(tsteps, one, ext)
    out_ref[:] = ext[t:-t, t:-t]


def _shard_vmem_chunk(u, strips, scalars, tsteps, cx, cy, nx, ny,
                      step=_step_value_literal):
    """Whole-block-resident route: one program assembles the extended
    block in VMEM from the block and its four halo strips, advances it
    ``tsteps`` steps, and writes back only the (bm, bn) center."""
    north, south, west, east = strips
    mspace, smem = _mem_spaces()
    kwargs = dict(
        in_specs=[pl.BlockSpec(**smem)] + [pl.BlockSpec(**mspace)] * 5,
        out_specs=pl.BlockSpec(**mspace))
    return pl.pallas_call(
        functools.partial(_shard_fused_vmem_kernel, tsteps=tsteps,
                          nx=nx, ny=ny, cx=cx, cy=cy, step=step),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        interpret=_interpret(),
        input_output_aliases={4: 0},
        **kwargs)(scalars, west, east, north, u, south)


def _strip_windows(strip, nblk, rb, t):
    """(nblk, rb+2t, t) per-band windows of a (nblk*rb + 2t, t) column
    strip: band i's window covers its extended rows [i*rb - t,
    i*rb + rb + t) in strip coordinates [i*rb, i*rb + rb + 2t) — the
    _row_strips band-neighbor gather applied to the strip's own blocks,
    with the strip's corner rows as the outer tail/head."""
    core = strip[t:-t].reshape(nblk, rb, strip.shape[1])
    tails, heads = _row_strips(core, t, strip[:t][None], strip[-t:][None])
    return jnp.concatenate([tails, core, heads], axis=1)


def _shard_band_chunk(u, strips, scalars, tsteps, cx, cy, nx, ny,
                      step=_step_value_literal, bm=None):
    """Stream the block in temporally-blocked row bands, halo strips as
    operands.

    Same staleness schedule as kernel C: each band's extended rows (its
    2t-deep row strips — exact neighbor data at sweep start, from the
    adjacent bands or the N/S halo) degrade one row per in-VMEM step, so
    after t steps the band's rb-row center is exact. The column strips
    are pre-gathered into per-band (rb+2t, t) windows (_strip_windows)
    so each program's VMEM holds only its own window. Uneven row counts
    embed the south strip directly below the domain rows before padding,
    so every band's down-strip reads the right rows; pad garbage lives
    strictly below the kept output.
    """
    t = tsteps
    m, n = u.shape
    north, south, west, east = strips
    if bm is None:
        # Kernel D's envelope is tighter than kernel C's: the pipelined
        # u/out blocks and strip operands double-buffer on top of the
        # extended-block working set. Probed on the v5e (windowed
        # strips, T=8): ext blocks ~1.25 MB compile everywhere
        # (rb=128@2048-wide, 64@4096, 32-40@8192); ~1.75 MB is
        # borderline; 2 MB-class plans OOM the compiler's scoped VMEM.
        # budget//8 (1 MB at v5e) keeps every width in the probed-safe
        # region.
        rb, m_pad = plan_bands(m, n, u.dtype,
                               target_bytes=vmem_budget_bytes() // 8)
    else:
        rb, m_pad = _resolve_bands(m, n, u.dtype, bm)
    if rb < t:
        # A band must source its t-deep row strip from ONE adjacent band,
        # so rb < t cannot stream directly (tiny VMEM budget vs deep
        # halo). Assemble the extended block once and advance it with
        # depth-1 sweeps — the staleness cone allows it: after s sweeps
        # the outer s cells are stale and only the center is kept.
        vert = jnp.concatenate([north, u, south], axis=0)
        ext = jnp.concatenate([west, vert, east], axis=1)
        em, en = ext.shape
        z_row = jnp.zeros((1, en), u.dtype)
        z_col = jnp.zeros((em + 2, 1), u.dtype)
        for _ in range(t):
            ext = _shard_band_chunk(
                ext, (z_row, z_row, z_col, z_col), scalars - t, 1,
                cx, cy, nx, ny, step=step, bm=bm)
        return ext[t:-t, t:-t]
    # Per-program strip windows, lane-padded to 128 by Mosaic.
    strip_bytes = (2 * (rb + 2 * t) * max(t, 128)
                   * jnp.dtype(u.dtype).itemsize)
    _check_band_vmem(rb, t, n + 2 * t, u.dtype, extra_bytes=strip_bytes)
    if m_pad == m:
        nblk = m // rb
        u_in = u
        ups, dns = _row_strips(u.reshape(nblk, rb, n), t,
                               north[None], south[None])
    else:
        m_pad = -(-(m + t) // rb) * rb
        nblk = m_pad // rb
        u_in = jnp.pad(jnp.concatenate([u, south], axis=0),
                       ((0, m_pad - m - t), (0, 0)))
        ups, dns = _row_strips(u_in.reshape(nblk, rb, n), t, north[None],
                               jnp.zeros((1, t, n), u.dtype))
    if m_pad > m:
        # Column strips must cover the pad rows' windows too (values
        # there are discarded; the window arithmetic must not clamp).
        west = jnp.pad(west, ((0, m_pad - m), (0, 0)))
        east = jnp.pad(east, ((0, m_pad - m), (0, 0)))
    wwin = _strip_windows(west, nblk, rb, t)
    ewin = _strip_windows(east, nblk, rb, t)

    mspace, smem = _mem_spaces()
    grid_spec = pl.GridSpec(
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((2,), lambda i: (0,), **smem),
            pl.BlockSpec((1, rb + 2 * t, t), lambda i: (i, 0, 0), **mspace),
            pl.BlockSpec((1, rb + 2 * t, t), lambda i: (i, 0, 0), **mspace),
            pl.BlockSpec((1, t, n), lambda i: (i, 0, 0), **mspace),
            pl.BlockSpec((rb, n), lambda i: (i, 0), **mspace),
            pl.BlockSpec((1, t, n), lambda i: (i, 0, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((rb, n), lambda i: (i, 0), **mspace),
    )
    out = pl.pallas_call(
        functools.partial(_shard_fused_band_kernel, rb=rb, tsteps=tsteps,
                          nx=nx, ny=ny, cx=cx, cy=cy, step=step),
        out_shape=jax.ShapeDtypeStruct((m_pad, n), u.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        input_output_aliases={4: 0},
        **_parallel_grid(1))(scalars, wwin, ewin, ups, u_in, dns)
    return out[:m] if m_pad > m else out


# --------------------------------------------------------------------- #
# Kernel D2: gather-free shard sweeps for mode='hybrid'
# --------------------------------------------------------------------- #
#
# Kernel D's band route re-gathers the (nblk, T, n) row strips and the
# per-band column windows every chunk — the same non-overlapped XLA copy
# cost kernel C paid per sweep, plus bands capped at ~1 MB by its probed
# envelope. D2 is kernel C2's dataflow applied to the shard chunk:
#
# - The shard carry rides EXTENDED as (bm + T, bn): rows [0, bm) the
#   block, rows [bm, bm + T) the current sweep's SOUTH halo, updated in
#   place per sweep (a T-row dynamic_update_slice, not a block concat).
# - DOWN-strips ride in the same operand via a row-overlapping pl.Element
#   window of (rb + T) rows at i*rb: for interior bands those rows are
#   block i+1's still-old head (sequential grid ⇒ writes trail the read
#   frontier, the C2 race argument); for the LAST band they are exactly
#   the south-halo rows — real ppermute data, no overrun pad needed.
# - UP-strips relay through persistent (T, bn) VMEM scratch; program 0 —
#   whose up rows are the NORTH halo, not a previous band — selects the
#   north strip, riding as a small separate operand, over the scratch.
# - E/W column strips (only when the mesh has a y axis) come pre-windowed
#   per band exactly as kernel D does (_strip_windows).
#
# The keep mask depends on the shard's mesh position (traced x0/y0), so
# unlike C2 the interior fast path uses a TRACED pl.when predicate: a
# band branches to the mask-free body when its extended rows provably
# touch no global boundary, pad row, or (with cols) shard column halo.

def plan_shard_window(m: int, bn: int, tsteps: int, dtype=jnp.float32,
                      with_cols: bool = False) -> tuple[int, int] | None:
    """(rb, m_pad) for the D2 route, or None when the route is not
    viable: off-TPU (pl.Element has no interpreter support — kernel D
    covers CPU tests), misaligned shapes (lane rule bn % 128, sublane
    rules rb % 8 / T % 8), or no in-envelope band height.

    Divisor-poor (or non-8-aligned) shard heights PAD to an rb multiple
    instead of dropping to kernel D's ~1 MB gathered bands (the VERDICT
    r4 weak-#4 cliff: a 1048-row shard fell from the window route to a
    tens-of-percent-slower fallback with no warning). The padded carry
    keeps the south halo DIRECTLY below the domain rows — rows
    [bm, bm+T) — with the inert pad after it, so the first garbage row
    at sweep start is always bm+T and the staleness cone never reaches
    a domain row (the same embedding kernel D's uneven-band path uses,
    _shard_band_chunk)."""
    if not (_on_tpu() and _compiler_params_cls() is not None):
        return None
    if bn % 128 or tsteps % 8 or tsteps < 8 or m < 8:
        return None
    ext = _window_ext_rows(bn * jnp.dtype(dtype).itemsize, tsteps)
    if with_cols:
        # The two lane-padded (rb+2T, 128) strip windows double-buffer on
        # top of the C2 working set. D2's kernel measures a LOOSER
        # with-cols envelope than C3's (rb=512 at 4 KB rows compiles
        # here where C3 breaks at 480 ext rows — different operand
        # structure); the -8 allowance is the probed D2 rule, and
        # tpu_smoke compiles the pod-relevant 16 KB shard width to keep
        # it honest.
        ext -= 8
    if min(ext - 2 * tsteps, m) // 8 * 8 <= 2 * tsteps:
        return None
    rb, m_pad = plan_from_ext(m, min(ext, m + 2 * tsteps), tsteps)
    if rb <= 2 * tsteps or rb % 8:
        return None
    return rb, m_pad


def _shard_window_kernel(with_cols, resid, s_ref, n_ref, *refs, rb,
                         tsteps, nsub, nx, ny, cx, cy, step,
                         valid_rows=None):
    if with_cols:
        if resid:
            w_ref, e_ref, u_ref, out_ref, r_ref, tail = refs
        else:
            w_ref, e_ref, u_ref, out_ref, tail = refs
    else:
        if resid:
            u_ref, out_ref, r_ref, tail = refs
        else:
            u_ref, out_ref, tail = refs
    i = pl.program_id(0)
    t = tsteps
    x0, y0 = s_ref[0], s_ref[1]
    bn = u_ref.shape[1]
    up = jnp.where(i == 0, n_ref[:], tail[:])
    tail[:] = u_ref[rb - t:rb, :]          # original tail, for band i+1
    ext = jnp.concatenate([up, u_ref[:]], axis=0)     # (rb + 2t, bn)
    row0 = x0 + i * rb - t
    gi = row0 + lax.broadcasted_iota(jnp.int32, (rb + 2 * t, 1), 0)
    keep = (gi <= 0) | (gi >= nx - 1)
    needs = (row0 <= 0) | (row0 + rb + 2 * t > nx - 1)
    if with_cols:
        ext = jnp.concatenate([w_ref[0], ext, e_ref[0]], axis=1)
        gj = (y0 - t
              + lax.broadcasted_iota(jnp.int32, (1, bn + 2 * t), 1))
        keep = keep | (gj <= 0) | (gj >= ny - 1)
        needs = needs | (y0 <= t) | (y0 + bn + t > ny - 1)
        center = (slice(t, -t), slice(t, -t))
    else:
        # Full-width bands: the step form itself keeps the first/last
        # columns, which ARE the global y boundary (y0 == 0, bn == ny) —
        # the row-only mask C2 uses.
        center = (slice(t, -t), slice(None))

    def masked(v):
        return jnp.where(keep, v, step(v, cx, cy))

    if resid:
        # D2R: track the final plane pair and emit this band's partial
        # Σ(Δu)² (the C2R design on the shard sweep). Single masked
        # body, steps inlined — once per INTERVAL, and dual pl.when
        # bodies of inlined steps double Mosaic's VMEM stack.
        v = ext
        for _ in range(nsub - 1):
            v = masked(v)
        prev = v
        last = masked(v)
        out_ref[:] = last[center]
        d = last[center] - prev[center]
        if valid_rows is not None:
            # Padded plans (plan_shard_window): band centers past the
            # shard's true height cover overwritten south-halo/pad rows
            # whose deltas are garbage — and on an INTERIOR shard the
            # global keep mask does not cover them (their gi sits in
            # the neighbor's domain range). Zero them out of the
            # residual (review r5).
            li = i * rb + lax.broadcasted_iota(jnp.int32, (rb, 1), 0)
            d = jnp.where(li < valid_rows, d, 0.0)
        r_ref[...] = jnp.sum(d * d).reshape(1, 1, 1)
        return
    if nsub < tsteps:
        # Partial-depth sweep (chunk remainders): single masked body,
        # same stack rule as above; _window_steps inlines the short run.
        out_ref[:] = _window_steps(nsub, masked, ext)[center]
        return

    @pl.when(needs)
    def _():
        out_ref[:] = _unrolled_steps(t, masked, ext)[center]

    @pl.when(jnp.logical_not(needs))
    def _():
        out_ref[:] = _unrolled_steps(
            t, lambda v: step(v, cx, cy), ext)[center]


def shard_window_sweep(ue, north, west, east, scalars, *, rb, tsteps,
                       nx, ny, cx, cy, step=_step_value, nsub=None,
                       resid=False, valid_rows=None):
    """One sweep over the extended shard carry ``ue`` of (m_pad + T, bn)
    — rows [0, bm) the block, [bm, bm+T) the south halo, [bm+T,
    m_pad+T) inert pad when rb does not divide bm (plan_shard_window).
    ``west``/``east``: None (no y axis) or (nblk, rb+2T, T) per-band
    windows of the exchanged column strips. In-place via alias; with
    pad, band centers overwrite the south/pad rows with stale values —
    harmless, since the south refreshes from ppermute before every
    sweep and pad rows are never read as exact (the first garbage row
    at sweep start is always bm+T, one full halo depth below the last
    domain row).

    ``nsub``: steps to advance (<= T; default T) — partial-depth chunk
    remainders stay on the window route. ``resid=True`` (D2R): returns
    ``(ue_new, partials)`` where ``partials`` sums per band to this
    SHARD's Σ(Δu)² of the final plane pair; callers psum it across the
    mesh for the global residual, and on padded plans must pass
    ``valid_rows=bm`` (the shard's true height) so pad-row garbage
    deltas are excluded."""
    mt, bn = ue.shape
    t = tsteps
    nblk = (mt - t) // rb
    with_cols = west is not None
    mspace, smem = _mem_spaces()
    params = _compiler_params_cls()       # non-None: plan gated the route
    in_specs = [pl.BlockSpec((2,), lambda i: (0,), **smem),
                pl.BlockSpec((t, bn), lambda i: (0, 0), **mspace)]
    args = [scalars, north]
    if with_cols:
        spec = pl.BlockSpec((1, rb + 2 * t, t), lambda i: (i, 0, 0),
                            **mspace)
        in_specs += [spec, spec]
        args += [west, east]
    in_specs.append(pl.BlockSpec((pl.Element(rb + t), pl.Element(bn)),
                                 lambda i: (i * rb, 0), **mspace))
    args.append(ue)
    out_shape = [jax.ShapeDtypeStruct(ue.shape, ue.dtype)]
    out_specs = [pl.BlockSpec((rb, bn), lambda i: (i, 0), **mspace)]
    if resid:
        # (nblk, 1, 1) partials with (1, 1, 1) blocks — the Mosaic
        # scalar-block layout (see _window_resid_sweep).
        out_shape.append(jax.ShapeDtypeStruct((nblk, 1, 1), jnp.float32))
        out_specs.append(pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0),
                                      **mspace))
    out = pl.pallas_call(
        functools.partial(_shard_window_kernel, with_cols, resid, rb=rb,
                          tsteps=t, nsub=t if nsub is None else nsub,
                          nx=nx, ny=ny, cx=cx, cy=cy, step=step,
                          valid_rows=valid_rows),
        out_shape=out_shape if resid else out_shape[0],
        grid=(nblk,),
        in_specs=in_specs,
        out_specs=out_specs if resid else out_specs[0],
        scratch_shapes=[pltpu.VMEM((t, bn), ue.dtype)],
        input_output_aliases={len(args) - 1: 0},
        compiler_params=params(dimension_semantics=("arbitrary",)),
    )(*args)
    if resid:
        return out[0], jnp.sum(out[1])
    return out


def make_shard_chunk_kernel(config):
    """``chunk_kernel(u, strips, t, x0, y0) -> u_new`` for mode='hybrid':
    advances the (bm, bn) shard block t steps in one Pallas invocation,
    taking the four t-deep halo strips (parallel.halo.exchange_halo_strips)
    as operands — the extended block only ever exists in VMEM, and the
    result is the exact center directly. x0/y0 are the global coordinates
    of u[0, 0] (traced, from lax.axis_index).

    Step form: the FMA factoring (_step_value) by default — same numeric
    class as mode='pallas'; ``config.bitwise_parity`` selects the literal
    reference expression, making hybrid BITWISE identical to serial mode
    (the hybrid parity tests pin that path)."""
    cx, cy = config.cx, config.cy
    nx, ny = config.nxprob, config.nyprob
    step = (_step_value_literal if getattr(config, "bitwise_parity", False)
            else _step_value)

    def chunk_kernel(u, strips, t, x0, y0):
        scalars = jnp.stack([jnp.asarray(x0, jnp.int32),
                             jnp.asarray(y0, jnp.int32)])
        m, n = u.shape
        if fits_vmem((m + 2 * t, n + 2 * t), u.dtype):
            return _shard_vmem_chunk(u, strips, scalars, t, cx, cy,
                                     nx, ny, step=step)
        return _shard_band_chunk(u, strips, scalars, t, cx, cy,
                                 nx, ny, step=step)

    return chunk_kernel


# --------------------------------------------------------------------- #
# Kernel F: fused in-kernel ICI halo exchange for mode='hybrid'
# --------------------------------------------------------------------- #
#
# Every route above receives its halo strips as OPERANDS: the XLA-level
# ppermute completes (a collective data dependency) before the chunk
# kernel may launch — one barrier per chunk of T steps, the cost ROADMAP
# item 2 names. Kernel F moves the exchange into the kernel itself,
# reproducing the reference's persistent-nonblocking-MPI overlap
# (grad1612_mpi_heat.c:233-259: MPI_Startall -> update inner cells ->
# Waitall recv -> update boundary strips) at ICI speed:
#
# - One invocation per shard (inside shard_map), whole block VMEM-
#   resident (the band-streamed fallback stays on the collective route —
#   docs/SCALING.md fallback matrix).
# - Entry barrier with the 4 neighbors (get_barrier_semaphore): a remote
#   write may only land once its target has entered this invocation —
#   the recv buffers are per-invocation scratch.
# - Phase 1: async remote copies of the first/last T rows to the N/S
#   neighbors' recv buffers (pltpu.make_async_remote_copy), then the
#   INTERIOR sweep — T steps on the local block, exact at distance >= T
#   from the block edge — runs while the row strips are in flight.
# - Phase 2: N/S arrivals waited; the vertically-extended edge columns
#   (which carry the corner data — the same two-phase scheme as
#   parallel.halo.exchange_halo_strips) are assembled into send buffers
#   and dispatched E/W; the N/S boundary frames are recomputed while the
#   column strips fly.
# - Phase 3: E/W arrivals waited; the full-height W/E frames (corners
#   included) are computed and the four frames + interior stitched into
#   the output. Send completions are drained before exit so the source
#   block can be reused by the next chunk.
#
# Buffer slots are direction-keyed (0=N arrival, 1=S, 2=W, 3=E) on both
# the send and recv semaphore arrays — double-buffered in the sense that
# sends read the immutable input block / dedicated send staging while
# arrivals land in dedicated recv scratch, so communication never
# contends with the sweep's working set. Absent neighbors (mesh edge)
# zero-fill their recv buffer instead of waiting — MPI_PROC_NULL
# semantics, identical to the partial ppermute's zeros, so results stay
# BITWISE equal to the collective hybrid route (each kept cell's
# per-step arithmetic DAG is kernel D's; tpu_smoke pins this on
# hardware).

#: collective_id for kernel F's barrier/RDMA semaphores — any value
#: agreed across devices; distinct from 0 to stay clear of other
#: collectives a surrounding program might schedule.
_FUSED_ICI_COLLECTIVE_ID = 7


def _device_id_type():
    """The DeviceIdType for mesh-coordinate-tuple device ids: MESH
    where the enum still has it (jax<=0.4.x — LOGICAL there means a
    single flat index), LOGICAL on builds that folded tuples into it."""
    return (getattr(pltpu.DeviceIdType, "MESH", None)
            or pltpu.DeviceIdType.LOGICAL)


def _fused_compiler_params(params_cls):
    """CompilerParams for kernel F across jax versions: collective_id
    is required for the barrier/RDMA semaphores; has_side_effects only
    exists (and is only needed) on newer builds."""
    import dataclasses
    names = {f.name for f in dataclasses.fields(params_cls)}
    kw = {}
    if "collective_id" in names:
        kw["collective_id"] = _FUSED_ICI_COLLECTIVE_ID
    if "has_side_effects" in names:
        kw["has_side_effects"] = True
    return params_cls(**kw)


def remote_dma_supported() -> bool:
    """True when in-kernel async remote copies can lower here: on TPU
    (Mosaic — interpreter mode has no RDMA semantics) with a pallas
    build exposing the remote-copy + semaphore API."""
    return (_on_tpu()
            and hasattr(pltpu, "make_async_remote_copy")
            and hasattr(pltpu, "SemaphoreType")
            and hasattr(pltpu, "get_barrier_semaphore")
            and hasattr(pltpu, "DeviceIdType"))


def fused_ici_est_bytes(bm: int, bn: int, t: int, itemsize: int = 4) -> int:
    """VMEM working-set estimate for one kernel-F invocation: block +
    output + the sweep carry (~3 block-sized arrays, as fits_vmem
    charges the resident kernels), the N/S recv strips, and the four
    column staging/recv buffers — whose t-wide minor dim Mosaic
    lane-pads to 128 (the kernel-D lesson), plus the frame sweeps'
    (bm+2t, 3t)-class temporaries charged at the same padded width."""
    block = bm * bn * itemsize
    row_strips = 2 * t * bn * itemsize
    col_pad = max(3 * t, 128)
    col_strips = 8 * (bm + 2 * t) * col_pad * itemsize
    frame_rows = 4 * 3 * t * bn * itemsize
    return 3 * block + row_strips + col_strips + frame_rows


def fused_ici_viable(bm: int, bn: int, t: int, dtype=jnp.float32) -> bool:
    """Gate for kernel F: remote DMA must lower, the overlap geometry
    must tile the block (strict — empty regions have no Mosaic store),
    and the working set must clear the hard limit. Non-viable fused
    requests DEGRADE to the collective hybrid route (parallel.sharded
    owns the fallback; it never errors)."""
    if not remote_dma_supported():
        return False
    if t < 1 or bm <= 2 * t or bn <= 2 * t:
        return False
    return (fused_ici_est_bytes(bm, bn, t, jnp.dtype(dtype).itemsize)
            <= vmem_hard_limit_bytes())


def _fused_ici_kernel(s_ref, u_ref, out_ref, nrecv, srecv, wrecv, erecv,
                      wsend, esend, send_sem, recv_sem, *,
                      bm, bn, gx, gy, tsteps, nx, ny, cx, cy, step):
    t = tsteps
    ix, iy = s_ref[0], s_ref[1]
    x0, y0 = s_ref[2], s_ref[3]
    has_n, has_s = ix > 0, ix < gx - 1
    has_w, has_e = iy > 0, iy < gy - 1

    def advance(v, row_shift, col_shift):
        """T masked steps on a region whose ext (0,0) sits at global
        (x0+row_shift, y0+col_shift) — the kernel-D per-cell DAG, so
        kernel F is bitwise-equal to the collective hybrid route."""
        keep = _shard_keep_mask(x0, y0, v.shape, nx, ny,
                                row_shift=row_shift, col_shift=col_shift)
        return _unrolled_steps(
            t, lambda w: jnp.where(keep, w, step(w, cx, cy)), v)

    # PROC_NULL semantics: an absent neighbor's recv buffer reads as
    # zeros (its matching sender is absent too, so no write can land).
    for pred, buf in ((has_n, nrecv), (has_s, srecv),
                      (has_w, wrecv), (has_e, erecv)):
        @pl.when(jnp.logical_not(pred))
        def _(buf=buf):
            buf[...] = jnp.zeros_like(buf)

    # Entry barrier with the existing neighbors.
    barrier = pltpu.get_barrier_semaphore()
    neighbors = ((has_n, -1, 0), (has_s, 1, 0),
                 (has_w, 0, -1), (has_e, 0, 1))
    for pred, dix, diy in neighbors:
        @pl.when(pred)
        def _(dix=dix, diy=diy):
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=(ix + dix, iy + diy),
                device_id_type=_device_id_type())
    nnb = (has_n.astype(jnp.int32) + has_s.astype(jnp.int32)
           + has_w.astype(jnp.int32) + has_e.astype(jnp.int32))
    pltpu.semaphore_wait(barrier, nnb)

    def start_copy(pred, src, dst, slot, dix, diy):
        # Slot convention (agreed SPMD-wide): the slot names the
        # ARRIVAL direction on the destination, so sender and receiver
        # index the same semaphore cell.
        @pl.when(pred)
        def _():
            pltpu.make_async_remote_copy(
                src, dst, send_sem.at[slot], recv_sem.at[slot],
                device_id=(ix + dix, iy + diy),
                device_id_type=_device_id_type()).start()

    # Phase 1: row strips fly south/north...
    start_copy(has_s, u_ref.at[pl.ds(bm - t, t), :], nrecv, 0, 1, 0)
    start_copy(has_n, u_ref.at[pl.ds(0, t), :], srecv, 1, -1, 0)
    # ...while the interior sweep runs on local data only.
    core = advance(u_ref[:], 0, 0)
    out_ref[t:bm - t, t:bn - t] = core[t:bm - t, t:bn - t]

    for pred, slot in ((has_n, 0), (has_s, 1)):
        @pl.when(pred)
        def _(slot=slot):
            pltpu.semaphore_wait(recv_sem.at[slot], 1)

    # Phase 2: vertically-extended edge columns (corners ride along)
    # fly east/west while the N/S frames recompute.
    esend[...] = jnp.concatenate(
        [nrecv[:, bn - t:], u_ref[:, bn - t:], srecv[:, bn - t:]], axis=0)
    wsend[...] = jnp.concatenate(
        [nrecv[:, :t], u_ref[:, :t], srecv[:, :t]], axis=0)
    start_copy(has_e, esend, wrecv, 2, 0, 1)
    start_copy(has_w, wsend, erecv, 3, 0, -1)

    nfr = advance(jnp.concatenate([nrecv[:], u_ref[:2 * t, :]], axis=0),
                  -t, 0)
    out_ref[0:t, t:bn - t] = nfr[t:2 * t, t:bn - t]
    sfr = advance(jnp.concatenate([u_ref[bm - 2 * t:, :], srecv[:]],
                                  axis=0), bm - 2 * t, 0)
    out_ref[bm - t:bm, t:bn - t] = sfr[t:2 * t, t:bn - t]

    for pred, slot in ((has_w, 2), (has_e, 3)):
        @pl.when(pred)
        def _(slot=slot):
            pltpu.semaphore_wait(recv_sem.at[slot], 1)

    # Phase 3: full-height W/E frames (corners included), then stitch.
    wext = jnp.concatenate(
        [wrecv[:], jnp.concatenate([nrecv[:, :2 * t], u_ref[:, :2 * t],
                                    srecv[:, :2 * t]], axis=0)], axis=1)
    wfr = advance(wext, -t, -t)
    out_ref[0:bm, 0:t] = wfr[t:bm + t, t:2 * t]
    eext = jnp.concatenate(
        [jnp.concatenate([nrecv[:, bn - 2 * t:], u_ref[:, bn - 2 * t:],
                          srecv[:, bn - 2 * t:]], axis=0), erecv[:]],
        axis=1)
    efr = advance(eext, -t, bn - 2 * t)
    out_ref[0:bm, bn - t:bn] = efr[t:bm + t, t:2 * t]

    # Drain send completions: the block may be rewritten next chunk.
    for pred, slot in ((has_s, 0), (has_n, 1), (has_e, 2), (has_w, 3)):
        @pl.when(pred)
        def _(slot=slot):
            pltpu.semaphore_wait(send_sem.at[slot], 1)


def make_fused_chunk_kernel(config, axes_info):
    """Kernel F entry for parallel.sharded: ``fused(u, t, ix, iy, x0,
    y0) -> u_new`` advancing the (bm, bn) shard block t steps with the
    halo exchange fused into the kernel as async remote copies, or
    ``None`` when remote DMA cannot lower here (off-TPU, old pallas,
    single-device mesh) — the caller then keeps the collective route.
    ``fused.viable(t)`` gates per chunk depth (geometry + VMEM), so
    remainder chunks degrade independently. ``axes_info`` is the
    sharded runner's (ax, ay, gx, gy); kernel F only supports the
    plain 2-axis hybrid mesh (device ids are (x, y) mesh coordinates).
    """
    if not remote_dma_supported():
        return None
    _, _, gx, gy = axes_info
    if gx * gy == 1:
        return None        # no neighbors — nothing to fuse
    nx, ny = config.nxprob, config.nyprob
    bm = (-(-nx // gx) * gx) // gx
    bn = (-(-ny // gy) * gy) // gy
    cx, cy = config.cx, config.cy
    step = (_step_value_literal if getattr(config, "bitwise_parity", False)
            else _step_value)
    mspace, smem = _mem_spaces()
    params = _compiler_params_cls()

    def fused(u, t, ix, iy, x0, y0):
        scalars = jnp.stack([jnp.asarray(ix, jnp.int32),
                             jnp.asarray(iy, jnp.int32),
                             jnp.asarray(x0, jnp.int32),
                             jnp.asarray(y0, jnp.int32)])
        return pl.pallas_call(
            functools.partial(_fused_ici_kernel, bm=bm, bn=bn, gx=gx,
                              gy=gy, tsteps=t, nx=nx, ny=ny, cx=cx,
                              cy=cy, step=step),
            out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
            in_specs=[pl.BlockSpec(**smem), pl.BlockSpec(**mspace)],
            out_specs=pl.BlockSpec(**mspace),
            scratch_shapes=[
                pltpu.VMEM((t, bn), u.dtype),           # nrecv
                pltpu.VMEM((t, bn), u.dtype),           # srecv
                pltpu.VMEM((bm + 2 * t, t), u.dtype),   # wrecv
                pltpu.VMEM((bm + 2 * t, t), u.dtype),   # erecv
                pltpu.VMEM((bm + 2 * t, t), u.dtype),   # wsend
                pltpu.VMEM((bm + 2 * t, t), u.dtype),   # esend
                pltpu.SemaphoreType.DMA((4,)),          # send slots
                pltpu.SemaphoreType.DMA((4,)),          # recv slots
            ],
            compiler_params=_fused_compiler_params(params),
        )(scalars, u)

    fused.viable = lambda t: fused_ici_viable(bm, bn, t)
    return fused
