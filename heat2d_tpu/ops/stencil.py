"""5-point Jacobi stencil — the numeric core (golden jnp model).

The reference implements ``u' = u + cx*(uE + uW - 2u) + cy*(uN + uS - 2u)``
four times over (mpi_heat2Dn.c:225-237, grad1612_mpi_heat.c:239-259,
grad1612_hybrid_heat.c:256-281, grad1612_cuda_heat.cu:55-62 — SURVEY.md A.9).
This module is the single source of truth for the math; the Pallas kernel
(heat2d_tpu/ops/pallas_stencil.py) and the sharded engines are tested
against it.

Boundary semantics: edge cells are never updated (loop bounds in the
reference, e.g. mpi_heat2Dn.c:228-229, guard grad1612_cuda_heat.cu:58) —
they keep their initial value, which the initial condition makes 0 (the
clamped/absorbing boundary of readme.md:3-5).

Precision semantics (SURVEY.md Appendix B): storage is float32 everywhere in
the reference, but C promotes each update through double because CX/CY/2.0
are double literals. ``accum_dtype=float64`` reproduces that exactly
(compute in f64, store f32); ``float32`` is the TPU-fast path.
"""

from __future__ import annotations

import jax.numpy as jnp


def _laplacian_update(v, cx, cy):
    """Stencil applied to the interior of a (halo-inclusive) array ``v``.

    Returns updated values for v[1:-1, 1:-1] in v's dtype.
    """
    c = v[1:-1, 1:-1]
    return (c
            + cx * (v[2:, 1:-1] + v[:-2, 1:-1] - 2.0 * c)
            + cy * (v[1:-1, 2:] + v[1:-1, :-2] - 2.0 * c))


def stencil_step(u: jnp.ndarray, cx: float, cy: float,
                 accum_dtype=jnp.float32) -> jnp.ndarray:
    """One global time step. Interior updated, edges held (clamped BC)."""
    v = u.astype(accum_dtype)
    cxa = jnp.asarray(cx, accum_dtype)
    cya = jnp.asarray(cy, accum_dtype)
    new_interior = _laplacian_update(v, cxa, cya).astype(u.dtype)
    return u.at[1:-1, 1:-1].set(new_interior)


def stencil_step_padded(padded: jnp.ndarray, cx: float, cy: float,
                        accum_dtype=jnp.float32) -> jnp.ndarray:
    """One step on a halo-padded local block.

    ``padded`` has shape (bm+2, bn+2): a (bm, bn) shard surrounded by a
    1-cell ghost ring (the reference's block_x × block_y halo'd block,
    grad1612_mpi_heat.c:50-52). Returns the updated (bm, bn) interior —
    *every* interior cell updated; global-boundary masking is the caller's
    job (the sharded engine knows the shard's mesh position, this op does
    not).
    """
    v = padded.astype(accum_dtype)
    cxa = jnp.asarray(cx, accum_dtype)
    cya = jnp.asarray(cy, accum_dtype)
    return _laplacian_update(v, cxa, cya).astype(padded.dtype)


def residual_sq(u_new: jnp.ndarray, u_old: jnp.ndarray,
                accum_dtype=jnp.float32) -> jnp.ndarray:
    """Local convergence residual: sum of squared per-cell deltas.

    The reference's locdiff (grad1612_mpi_heat.c:264-267), computed over the
    shard interior and summed across ranks with MPI_Allreduce; the engine
    psums this. Reference accumulates in float32; we follow accum_dtype.
    """
    d = u_new.astype(accum_dtype) - u_old.astype(accum_dtype)
    return jnp.sum(d * d)
