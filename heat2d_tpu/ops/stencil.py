"""5-point Jacobi stencil — the numeric core (golden jnp model).

The reference implements ``u' = u + cx*(uE + uW - 2u) + cy*(uN + uS - 2u)``
four times over (mpi_heat2Dn.c:225-237, grad1612_mpi_heat.c:239-259,
grad1612_hybrid_heat.c:256-281, grad1612_cuda_heat.cu:55-62 — SURVEY.md A.9).
This module is the single source of truth for the math; the Pallas kernel
(heat2d_tpu/ops/pallas_stencil.py) and the sharded engines are tested
against it.

Boundary semantics: edge cells are never updated (loop bounds in the
reference, e.g. mpi_heat2Dn.c:228-229, guard grad1612_cuda_heat.cu:58) —
they keep their initial value, which the initial condition makes 0 (the
clamped/absorbing boundary of readme.md:3-5).

Precision semantics (SURVEY.md Appendix B, sharpened): storage is float32
everywhere in the reference. In the C expression
``u + CX*(uE + uW - 2.0*u) + CY*(uN + uS - 2.0*u)`` the usual arithmetic
conversions make the *neighbor sums* ``uE + uW`` float32 (both operands are
float), while every operation touching the double literals CX/CY/2.0 is
performed in double and truncated to f32 on store. ``accum_dtype=float64``
reproduces exactly that mixed evaluation — verified bitwise against a
freshly compiled C oracle (tests/test_c_parity.py). ``float32`` is the
TPU-fast path (all-f32, identical formula).
"""

from __future__ import annotations

import jax.numpy as jnp


def _laplacian_update(v, cx, cy, accum_dtype=None):
    """Stencil applied to the interior of a (halo-inclusive) array ``v``.

    Returns updated values for v[1:-1, 1:-1] in ``accum_dtype`` (default:
    v's dtype). Neighbor sums are evaluated in v's dtype before promotion
    — the C semantics above.
    """
    accum = v.dtype if accum_dtype is None else accum_dtype
    c = v[1:-1, 1:-1].astype(accum)
    # sx: axis-0 (ix±1) neighbor sum — pairs with cx, as in the reference
    # (CX multiplies the ix neighbors, grad1612_cuda_heat.cu:59-61);
    # sy: axis-1 (iy±1) sum — pairs with cy.
    sx = (v[2:, 1:-1] + v[:-2, 1:-1]).astype(accum)
    sy = (v[1:-1, 2:] + v[1:-1, :-2]).astype(accum)
    cx = jnp.asarray(cx, accum)
    cy = jnp.asarray(cy, accum)
    return c + cx * (sx - 2.0 * c) + cy * (sy - 2.0 * c)


def stencil_step(u: jnp.ndarray, cx: float, cy: float,
                 accum_dtype=jnp.float32) -> jnp.ndarray:
    """One global time step. Interior updated, edges held (clamped BC)."""
    new_interior = _laplacian_update(u, cx, cy, accum_dtype).astype(u.dtype)
    return u.at[1:-1, 1:-1].set(new_interior)


def stencil_step_padded(padded: jnp.ndarray, cx: float, cy: float,
                        accum_dtype=jnp.float32) -> jnp.ndarray:
    """One step on a halo-padded local block.

    ``padded`` has shape (bm+2, bn+2): a (bm, bn) shard surrounded by a
    1-cell ghost ring (the reference's block_x × block_y halo'd block,
    grad1612_mpi_heat.c:50-52). Returns the updated (bm, bn) interior —
    *every* interior cell updated; global-boundary masking is the caller's
    job (the sharded engine knows the shard's mesh position, this op does
    not).
    """
    return _laplacian_update(padded, cx, cy, accum_dtype).astype(padded.dtype)


def stencil_step_var(u: jnp.ndarray, kx: jnp.ndarray, ky: jnp.ndarray,
                     accum_dtype=None) -> jnp.ndarray:
    """One global time step with PER-CELL diffusivities — the
    variable-coefficient (heterogeneous-material) forward update, and
    the second differentiable route of ``heat2d_tpu/diff``.

    ``kx``/``ky`` are full (nx, ny) fields; cell (i, j)'s update uses
    ``kx[i, j]``/``ky[i, j]`` exactly where the constant route uses
    cx/cy, so ``stencil_step_var(u, full(cx), full(cy))`` is bitwise
    ``stencil_step(u, cx, cy, accum_dtype=None)``. Edge cells are held
    (clamped BC), identical to ``stencil_step``; edge values of the
    coefficient fields are therefore inert. ``accum_dtype=None``
    accumulates in u's dtype (the all-f32 TPU-fast evaluation; pass
    float64 under x64 for the C-promotion semantics).

    Stability note (docs/DIFFERENTIABLE.md): the explicit scheme needs
    ``kx + ky <= 1/2`` pointwise; the inverse driver projects its
    recovered fields into that box after every optimizer step.
    """
    accum = u.dtype if accum_dtype is None else accum_dtype
    c = u[1:-1, 1:-1].astype(accum)
    sx = (u[2:, 1:-1] + u[:-2, 1:-1]).astype(accum)
    sy = (u[1:-1, 2:] + u[1:-1, :-2]).astype(accum)
    kxi = kx[1:-1, 1:-1].astype(accum)
    kyi = ky[1:-1, 1:-1].astype(accum)
    new_interior = c + kxi * (sx - 2.0 * c) + kyi * (sy - 2.0 * c)
    return u.at[1:-1, 1:-1].set(new_interior.astype(u.dtype))


def residual_sq(u_new: jnp.ndarray, u_old: jnp.ndarray,
                accum_dtype=jnp.float32) -> jnp.ndarray:
    """Local convergence residual: sum of squared per-cell deltas.

    The reference's locdiff (grad1612_mpi_heat.c:264-267), computed over the
    shard interior and summed across ranks with MPI_Allreduce; the engine
    psums this. Reference accumulates in float32; we follow accum_dtype.
    """
    d = u_new.astype(accum_dtype) - u_old.astype(accum_dtype)
    return jnp.sum(d * d)
