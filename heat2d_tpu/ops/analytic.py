"""Analytic separable-mode solutions — the accuracy oracle.

The clamped-boundary heat problem on the unit-spacing grid admits a
family of exact eigenmodes of the DISCRETE Laplacian:

    v[i, j] = sin(pi * i / (nx-1)) * sin(pi * j / (ny-1))

with ``dxx(v) = -lam_x * v`` on the interior, where
``lam_x = 4 * sin(pi / (2*(nx-1)))**2`` (and lam_y likewise). Under
the semi-discrete flow ``du/dt = alpha * (dxx + dyy) u`` the mode
decays EXACTLY as ``exp(-(lam_x + lam_y) * alpha * t)`` — so any time
discretization's error against this reference isolates the TIME
error alone (no spatial-truncation floor):

- explicit forward Euler:  per-step factor ``1 - cx*lam_x - cy*lam_y``
  -> global error O(dt),
- Crank-Nicolson ADI (Peaceman-Rachford, ``ops/tridiag.py``):
  per-step factor ``((1-a)(1-b)) / ((1+a)(1+b))`` with
  ``a = cx*lam_x/2``, ``b = cy*lam_y/2`` -> global error O(dt^2).

This is the ``accuracy`` column of the wall-clock-to-solution bench
block (``models/solution.py``, bench.py) and the convergence-order
tests (tests/test_implicit.py): both methods converge to the same
analytic answer, at their expected orders.

Time bookkeeping is dimensionless: ``that_x = cx * steps`` is
``alpha * t / dx**2``, so two runs reach the same physical time iff
their ``cx * steps`` (and ``cy * steps``) products match — the
matched-``t_final`` contract of the CI implicit-gate.
"""

from __future__ import annotations

import math

import numpy as np


def separable_mode(nx: int, ny: int, dtype=np.float32) -> np.ndarray:
    """The fundamental discrete eigenmode (unit amplitude, zero on
    every edge — compatible with the clamped boundary)."""
    ix = np.sin(np.pi * np.arange(nx, dtype=np.float64) / (nx - 1))
    iy = np.sin(np.pi * np.arange(ny, dtype=np.float64) / (ny - 1))
    return np.outer(ix, iy).astype(dtype)


def mode_eigenvalues(nx: int, ny: int) -> tuple:
    """(lam_x, lam_y) of the fundamental mode under the discrete
    second difference: ``dxx v = -lam_x v`` exactly."""
    return (4.0 * math.sin(math.pi / (2.0 * (nx - 1))) ** 2,
            4.0 * math.sin(math.pi / (2.0 * (ny - 1))) ** 2)


def mode_solution(nx: int, ny: int, that_x: float, that_y: float,
                  dtype=np.float32) -> np.ndarray:
    """The semi-discrete analytic solution at dimensionless times
    ``that_x = cx * steps`` / ``that_y = cy * steps``: the mode scaled
    by its exact exponential decay."""
    lx, ly = mode_eigenvalues(nx, ny)
    amp = math.exp(-(lx * that_x + ly * that_y))
    return (separable_mode(nx, ny, np.float64) * amp).astype(dtype)


def explicit_mode_factor(nx: int, ny: int, cx: float, cy: float) -> float:
    """Forward Euler's exact per-step amplification of the mode."""
    lx, ly = mode_eigenvalues(nx, ny)
    return 1.0 - cx * lx - cy * ly


def adi_mode_factor(nx: int, ny: int, cx: float, cy: float) -> float:
    """Peaceman-Rachford ADI's exact per-step amplification of the
    mode — |factor| < 1 for EVERY cx, cy > 0 (unconditional
    stability: both half-step rationals are A-stable)."""
    lx, ly = mode_eigenvalues(nx, ny)
    a, b = cx * lx / 2.0, cy * ly / 2.0
    return ((1.0 - a) * (1.0 - b)) / ((1.0 + a) * (1.0 + b))


def l2_error(u, ref) -> float:
    """Relative L2 error over the grid: ||u - ref|| / ||ref||."""
    u = np.asarray(u, np.float64)
    ref = np.asarray(ref, np.float64)
    denom = float(np.sqrt(np.sum(ref * ref)))
    if denom == 0.0:
        return float(np.sqrt(np.sum(u * u)))
    return float(np.sqrt(np.sum((u - ref) ** 2)) / denom)
