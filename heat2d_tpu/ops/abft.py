"""Algorithm-based fault tolerance (ABFT) checksums — silent data
corruption caught by algebra, not duplication (Huang & Abraham, IEEE
ToC 1984; see PAPERS.md).

Both time-steppers this repo serves are LINEAR in the grid state: one
explicit 5-point step is ``u' = A u`` (edges held, so ``A`` acts as
identity on the boundary ring), and one Peaceman-Rachford ADI step is
a rational function of the same split operators. A weighted checksum
``s_t = <w, u_t>`` therefore evolves by a CLOSED-FORM recurrence —
no second solve, no replica — when ``w`` is the discrete separable
sine mode (``ops/analytic.separable_mode``: zero on every edge, an
exact eigenvector of the interior second differences):

- **explicit** (jnp / pallas / band — bitwise-equal programs):

      s_{t+1} = alpha * s_t + beta
      alpha   = 1 - cx*lam_x - cy*lam_y        (the mode factor)
      beta    = cx*Bx + cy*By                  (boundary flux)

  ``Bx = sum_j w[1,j]*u[0,j] + w[nx-2,j]*u[nx-1,j]`` (and ``By``
  likewise) is the flux the held boundary ring pushes through the
  stencil's adjoint. Edge cells NEVER change (clamped BC), so beta is
  a constant of the run — computed once from ``u_0``.

- **adi** (``ops/tridiag``): with zero edges (the serving initial
  condition ``ops/init.inidat`` is zero on every edge) the mode is an
  exact eigenvector of both implicit half-steps, so ``beta = 0`` and
  ``alpha`` is the rational ADI amplification factor
  (``ops/analytic.adi_mode_factor``). Nonzero edges would push flux
  through the tridiagonal inverses — no constant-beta closed form —
  so ADI support REQUIRES zero-edge initial states (the caller's
  check; ``boundary_flux`` returning 0 is the witness).

- **mg** is an ITERATIVE approximation (residual-tolerance-limited),
  not an exact linear recurrence: unsupported, reported as such.

After ``k`` steps:  ``s_k = alpha^k s_0 + beta*(alpha^k-1)/(alpha-1)``
(``s_0 + k*beta`` at alpha == 1). The verify tier computes the
prediction from the launch's OWN inputs on-device (one weighted
reduction over ``u_0``), observes ``<w, u_k>`` both on-device (covers
in-compute corruption) and on the host buffer that will actually be
served (covers readback / host-memory corruption — the layer the
chaos harness can inject into without touching a traced value), and
classifies any residual beyond the roundoff tolerance as silent data
corruption.

Coverage is the honest ABFT contract (docs/RESILIENCE.md table): a
corruption is detected iff it moves the weighted sum past the
tolerance ``tol = factor * steps * eps(dtype) * scale`` — exponent
and sign-bit flips (value changes by O(|u|) or worse, often to
inf/nan) are caught at any grid size; low-order mantissa flips are
BELOW the accumulated-roundoff floor and pass, exactly as they are
numerically indistinguishable from legitimate roundoff. Overhead is
two weighted reductions per verified segment — O(nx*ny) against the
O(nx*ny*steps) solve, well under 1% for any real step count.
"""

from __future__ import annotations

import functools

import numpy as np

from heat2d_tpu.ops.analytic import separable_mode

#: methods whose per-step update is the explicit 5-point program
#: (bitwise-equal across these routes, so one recurrence covers all)
EXPLICIT_METHODS = frozenset({"jnp", "pallas", "band"})

#: ABFT family per resolved method; absent = unsupported
FAMILIES = {m: "explicit" for m in EXPLICIT_METHODS} | {"adi": "adi"}


def supported_family(method: str):
    """``"explicit"`` / ``"adi"`` for a RESOLVED method (post
    ``ensemble._pick_method`` — never ``"auto"``), else None."""
    return FAMILIES.get(method)


@functools.lru_cache(maxsize=32)
def mode_weights(nx: int, ny: int) -> np.ndarray:
    """The float64 checksum weight field (read-only; host side)."""
    w = separable_mode(nx, ny, np.float64)
    w.setflags(write=False)
    return w


def host_checksum(u, w=None) -> np.ndarray:
    """``<w, u>`` in float64 over the trailing two axes — the
    host-side observation of the buffer that is about to be served.
    ``u`` may be one grid or a batch."""
    with np.errstate(invalid="ignore"):   # a flipped bit may be a
        u = np.asarray(u, np.float64)     # signaling NaN — observe it
        if w is None:
            w = mode_weights(u.shape[-2], u.shape[-1])
        return np.einsum("...ij,ij->...", u, np.asarray(w, np.float64))


def step_factor(family: str, nx: int, ny: int, cx, cy):
    """Per-step checksum amplification ``alpha`` — ONE copy of the
    algebra: delegates to the analytic mode factors (pure arithmetic
    over ``mode_eigenvalues``, array-compatible), so the checksum
    prediction can never drift from the accuracy oracle the parity
    tests pin. ``cx``/``cy`` may be traced per-member vectors."""
    from heat2d_tpu.ops.analytic import (adi_mode_factor,
                                         explicit_mode_factor)

    if family == "explicit":
        return explicit_mode_factor(nx, ny, cx, cy)
    if family == "adi":
        return adi_mode_factor(nx, ny, cx, cy)
    raise ValueError(f"no ABFT family {family!r}")


def boundary_flux(u0, w, cx, cy):
    """The constant flux term ``beta`` of the explicit recurrence —
    exactly 0 for zero-edge states (the serving initial condition).
    ``u0``: (..., nx, ny); ``w``: (nx, ny); numpy or jnp arrays."""
    bx = ((w[1, 1:-1] * u0[..., 0, 1:-1]).sum(axis=-1)
          + (w[-2, 1:-1] * u0[..., -1, 1:-1]).sum(axis=-1))
    by = ((w[1:-1, 1] * u0[..., 1:-1, 0]).sum(axis=-1)
          + (w[1:-1, -2] * u0[..., 1:-1, -1]).sum(axis=-1))
    return cx * bx + cy * by


def _power(alpha, k):
    """``alpha ** k`` for traced float ``alpha`` (possibly NEGATIVE —
    the explicit factor crosses zero inside the stability box) and
    traced non-negative integer ``k``: ``lax.pow`` wants float
    exponents and NaNs on negative bases, so take ``|alpha|^k`` by
    exp/log with the parity sign restored, guarding ``k == 0`` and
    ``alpha == 0``."""
    import jax.numpy as jnp

    a = jnp.abs(alpha)
    kf = k.astype(a.dtype)
    mag = jnp.exp(kf * jnp.log(jnp.where(a > 0.0, a, 1.0)))
    mag = jnp.where(a > 0.0, mag, jnp.where(k == 0, 1.0, 0.0))
    sign = jnp.where((alpha < 0.0) & (k % 2 == 1), -1.0, 1.0)
    return jnp.where(k == 0, jnp.ones_like(alpha), mag * sign)


def predict(s0, alpha, beta, k):
    """``s_k`` by the closed-form recurrence (traced or numpy-scalar
    friendly via jnp)."""
    import jax.numpy as jnp

    ak = _power(alpha, k)
    kf = k.astype(ak.dtype) if hasattr(k, "astype") else float(k)
    geom = jnp.where(jnp.abs(alpha - 1.0) > 1e-6,
                     (ak - 1.0) / jnp.where(jnp.abs(alpha - 1.0) > 1e-6,
                                            alpha - 1.0, 1.0),
                     kf)
    return ak * s0 + beta * geom


def predict_batch(u0, cxs, cys, k, w, *, family: str):
    """Traced per-member prediction from a launch's own inputs:
    returns ``(s_pred, scale)`` for a ``(B, nx, ny)`` batch. ``w`` is
    the mode-weight field as a device array in ``u0``'s dtype; ``k``
    is the per-member step count (int32). ``scale`` is the magnitude
    the tolerance is relative to: ``<|w|, |u0|> + |s0| + k*|beta|``.
    """
    import jax.numpy as jnp

    s0 = jnp.einsum("bij,ij->b", u0, w)
    beta = (boundary_flux(u0, w, cxs, cys) if family == "explicit"
            else jnp.zeros_like(s0))
    alpha = step_factor(family, u0.shape[-2], u0.shape[-1], cxs, cys)
    s_pred = predict(s0, alpha, beta, k)
    scale = (jnp.einsum("bij,ij->b", jnp.abs(u0), jnp.abs(w))
             + jnp.abs(s0) + k.astype(s0.dtype) * jnp.abs(beta))
    return s_pred, scale


def observe_batch(u, w):
    """Traced on-device observation ``<w, u_k>`` per member."""
    import jax.numpy as jnp

    return jnp.einsum("bij,ij->b", u, w)


def tolerance(scale, steps, dtype=np.float32,
              factor: float = 64.0) -> np.ndarray:
    """Roundoff envelope for the residual ``|s_obs - s_pred|``: each
    f32 stencil step perturbs the weighted sum by O(eps * scale), so
    the accumulated drift is linear in the step count; ``factor``
    absorbs the reduction-order and exp/log constants (64 is ~10x the
    observed drift on the parity grids)."""
    eps = float(np.finfo(dtype).eps)
    steps = np.asarray(steps, np.float64)
    return factor * np.maximum(steps, 1.0) * eps * np.asarray(
        scale, np.float64)


def classify(s_obs, s_pred, scale, steps, dtype=np.float32,
             factor: float = 64.0) -> np.ndarray:
    """Boolean per-member corruption verdict: True where the residual
    escapes the tolerance OR the observation is non-finite (an
    exponent flip often lands on inf/nan, which no ``>`` would
    flag)."""
    s_obs = np.asarray(s_obs, np.float64)
    s_pred = np.asarray(s_pred, np.float64)
    tol = tolerance(scale, steps, dtype, factor)
    resid = np.abs(s_obs - s_pred)
    return (~np.isfinite(s_obs)) | (~np.isfinite(s_pred)) | (resid > tol)


def host_predict(u0, cx, cy, steps, *, method: str):
    """Host-side float64 mirror of ``predict_batch`` for ONE member —
    the test oracle (and a CLI-side verifier for saved fields)."""
    family = supported_family(method)
    if family is None:
        raise ValueError(f"method {method!r} has no ABFT recurrence")
    u0 = np.asarray(u0, np.float64)
    w = mode_weights(u0.shape[-2], u0.shape[-1])
    s0 = float(np.einsum("ij,ij->", u0, w))
    beta = (float(boundary_flux(u0, w, cx, cy))
            if family == "explicit" else 0.0)
    alpha = float(step_factor(family, u0.shape[-2], u0.shape[-1],
                              cx, cy))
    if steps == 0:
        return s0
    if abs(alpha - 1.0) > 1e-12:
        ak = alpha ** steps
        return ak * s0 + beta * (ak - 1.0) / (alpha - 1.0)
    return s0 + steps * beta
