"""Batched tridiagonal solves + Crank-Nicolson ADI — the implicit
time-stepping core (ROADMAP item 2: algorithmic speed).

The explicit 5-point kernel sits at 98% of the memory-bandwidth bound,
but its stability box (``cx + cy <= 1/2`` — ``ops/stability.py``)
makes reaching a physical time ``t_final`` cost O(1/dx^2) steps. The
Peaceman-Rachford ADI scheme here is UNCONDITIONALLY stable, so dt is
chosen by accuracy (O(dt^2) — twice the explicit order) and typically
100-1000x fewer steps reach the same answer at the same L2 error
(``ops/analytic.py`` is the oracle; ``models/solution.py`` turns the
comparison into the wall-clock-to-solution bench metric).

One ADI step at diffusion numbers (cx, cy) = alpha*dt/dx^2:

    half 1 (implicit in x):  (I - cx/2 dxx) u* = (I + cy/2 dyy) u
    half 2 (implicit in y):  (I - cy/2 dyy) u1 = (I + cx/2 dxx) u*

Each half is ny (resp. nx) INDEPENDENT constant-coefficient
tridiagonal systems — a natural batched Thomas solve:

- ``thomas_solve`` — the jnp golden model (lax.scan forward sweep +
  back substitution, systems batched over trailing axes), carrying a
  ``custom_vjp`` that IMPLICITLY differentiates the solve: the
  backward pass solves the TRANSPOSE tridiagonal system instead of
  unrolling the scan (``x = T^-1 b  =>  bbar = T^-T xbar``,
  ``Tbar = -lam xbar^T`` restricted to the three bands). This is what
  makes ``diff/adjoint.py``'s per-step pullback of the ADI operator
  an O(n) solve rather than an O(n) stored scan — validated against
  finite differences like PR 6 (tests/test_implicit.py).
- A Pallas kernel (kernel TD) solving many systems along the LANE
  dimension: the forward elimination's scalar recurrence runs in SMEM
  scratch while each row op is a full (1, w) lane vector — the
  sequential dependence lives on the 8-sublane axis, the parallelism
  on the 128-lane axis. The y half runs either as an explicit
  transpose + the same row kernel (``variant="xpose"``) or as a
  strided second pass eliminating along lanes (``variant="strided"``)
  — the two transpose strategies the autotune space measures
  (``tune/space.py`` routes "adi" / "adi_s").

Boundary semantics match the explicit kernels exactly: edge cells are
never updated (identity boundary rows; ``_hold_edges`` restores the
edge-column systems the lane-batched solve runs redundantly).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

#: Default lane-panel width for the row-solve kernel (the "adi" tune
#: space's bm axis): panels this wide keep the VPU lanes full while
#: bounding the per-program VMEM working set.
DEFAULT_PANEL = 512

#: Transpose strategies for the second (y) sweep.
VARIANTS = ("xpose", "strided")


# --------------------------------------------------------------------- #
# jnp golden model: scan-based Thomas with implicit differentiation
# --------------------------------------------------------------------- #

def _thomas_primal(dl, d, du, rhs):
    """Forward sweep + back substitution along axis 0. Bands are (n,)
    vectors; ``rhs`` is (n, ...) — every trailing slice an independent
    system. No pivoting: the CN matrices here are strictly diagonally
    dominant (|1 + c| > 2 * |c/2|), where Thomas is unconditionally
    stable."""
    n = rhs.shape[0]
    bshape = (n,) + (1,) * (rhs.ndim - 1)
    dlb = jnp.reshape(dl.astype(rhs.dtype), bshape)
    db = jnp.reshape(d.astype(rhs.dtype), bshape)
    dub = jnp.reshape(du.astype(rhs.dtype), bshape)

    def fwd(carry, row):
        cp_prev, dp_prev = carry
        dli, di, dui, bi = row
        m = di - dli * cp_prev
        cp = dui / m
        dp = (bi - dli * dp_prev) / m
        return (cp, dp), (cp, dp)

    zero = jnp.zeros_like(rhs[0])
    (_, _), (cps, dps) = lax.scan(fwd, (zero, zero),
                                  (dlb, db, dub, rhs))

    def back(x_next, row):
        cp, dp = row
        x = dp - cp * x_next
        return x, x

    _, xs = lax.scan(back, zero, (cps, dps), reverse=True)
    return xs


@jax.custom_vjp
def thomas_solve(dl, d, du, rhs):
    """Solve the tridiagonal system ``T x = rhs`` along axis 0, with
    ``T``'s bands (dl, d, du): row i reads
    ``dl[i]*x[i-1] + d[i]*x[i] + du[i]*x[i+1] = rhs[i]``
    (``dl[0]`` and ``du[n-1]`` are ignored by convention — pass 0).
    ``rhs`` may carry trailing batch axes (independent systems).

    Differentiable in all four arguments via IMPLICIT differentiation
    (module docstring): reverse-mode costs one transpose-system solve,
    never a stored elimination trace."""
    return _thomas_primal(dl, d, du, rhs)


def _thomas_fwd(dl, d, du, rhs):
    x = _thomas_primal(dl, d, du, rhs)
    return x, (dl, d, du, x)


def _thomas_bwd(res, xbar):
    dl, d, du, x = res
    # lam = T^-T xbar: the transpose's bands are the shifted originals
    # ((T^T)[i, i-1] = T[i-1, i] = du[i-1]).
    dl_t = jnp.concatenate([jnp.zeros((1,), du.dtype), du[:-1]])
    du_t = jnp.concatenate([dl[1:], jnp.zeros((1,), dl.dtype)])
    lam = _thomas_primal(dl_t, d, du_t, xbar)
    axes = tuple(range(1, x.ndim))
    zero_row = jnp.zeros_like(x[:1])
    x_up = jnp.concatenate([zero_row, x[:-1]])    # x[i-1]
    x_dn = jnp.concatenate([x[1:], zero_row])     # x[i+1]
    dl_bar = -jnp.sum(lam * x_up, axis=axes).astype(dl.dtype)
    d_bar = -jnp.sum(lam * x, axis=axes).astype(d.dtype)
    du_bar = -jnp.sum(lam * x_dn, axis=axes).astype(du.dtype)
    return dl_bar, d_bar, du_bar, lam


thomas_solve.defvjp(_thomas_fwd, _thomas_bwd)


# --------------------------------------------------------------------- #
# the CN-ADI step (jnp route)
# --------------------------------------------------------------------- #

def _cn_bands(n: int, c, dtype):
    """Bands of the half-step matrix ``I - (c/2) dxx`` with identity
    boundary rows (edges held — the clamped BC of every kernel in
    this repo): interior rows (-c/2, 1+c, -c/2), rows 0/n-1 (0, 1, 0).
    ``c`` may be a traced scalar — the bands are differentiable."""
    c = jnp.asarray(c, dtype)
    i = jnp.arange(n)
    interior = (i >= 1) & (i <= n - 2)
    a = jnp.where(interior, -0.5 * c, jnp.zeros((), dtype))
    d = jnp.where(interior, 1.0 + c, jnp.ones((), dtype))
    return a, d, a


def _rhs_half(u, c, axis: int):
    """``u + (c/2) * d2(u)`` along ``axis`` on the FULL interior,
    edges passed through unchanged (they are the held boundary values
    the identity rows consume). Works batched: ``u`` is (..., nx, ny)
    and ``axis`` counts from the grid dims (0 = rows, 1 = cols);
    ``c`` broadcasts (scalar, or (B, 1, 1) per-member)."""
    c = 0.5 * c
    ctr = u[..., 1:-1, 1:-1]
    if axis == 0:
        s = u[..., 2:, 1:-1] + u[..., :-2, 1:-1]
    else:
        s = u[..., 1:-1, 2:] + u[..., 1:-1, :-2]
    new = ctr + c * (s - 2.0 * ctr)
    mid = jnp.concatenate(
        [u[..., 1:-1, :1], new, u[..., 1:-1, -1:]], axis=-1)
    return jnp.concatenate([u[..., :1, :], mid, u[..., -1:, :]],
                           axis=-2)


def _hold_edges(v, u):
    """Restore the held boundary from ``u`` on all four edges of
    ``v`` (the lane-batched solves run the edge-column systems
    redundantly; identity rows already keep edge ROWS exact)."""
    mid = jnp.concatenate(
        [u[..., 1:-1, :1], v[..., 1:-1, 1:-1], u[..., 1:-1, -1:]],
        axis=-1)
    return jnp.concatenate([u[..., :1, :], mid, u[..., -1:, :]],
                           axis=-2)


def adi_step(u, cx, cy):
    """One Peaceman-Rachford ADI step on an (nx, ny) grid at diffusion
    numbers (cx, cy) — unconditionally stable, O(dt^2) accurate,
    edges held. Differentiable in (u, cx, cy): the tridiagonal solves
    carry the implicit-diff custom_vjp, so ``diff/adjoint.py`` can
    wrap this step exactly like the explicit one."""
    nx, ny = u.shape[-2], u.shape[-1]
    cx = jnp.asarray(cx, u.dtype)
    cy = jnp.asarray(cy, u.dtype)
    rhs1 = _rhs_half(u, cy, axis=1)
    dl, d, du = _cn_bands(nx, cx, u.dtype)
    ustar = _hold_edges(thomas_solve(dl, d, du, rhs1), u)
    rhs2 = _rhs_half(ustar, cx, axis=0)
    dl, d, du = _cn_bands(ny, cy, u.dtype)
    u1 = thomas_solve(dl, d, du, jnp.swapaxes(rhs2, -1, -2))
    return _hold_edges(jnp.swapaxes(u1, -1, -2), u)


def adi_multi_step(u, steps: int, cx, cy):
    """``steps`` ADI steps (jnp route). The band/elimination
    coefficients are loop-invariant — XLA hoists them out of the
    fori_loop, so the per-step cost is the two sweeps alone."""
    if steps == 0:
        return u
    return lax.fori_loop(0, steps,
                         lambda _, v: adi_step(v, cx, cy), u,
                         unroll=False)


# --------------------------------------------------------------------- #
# kernel TD: batched Thomas along the lane dimension (Pallas)
# --------------------------------------------------------------------- #

def _coeff_loops(s_ref, cp_ref, mi_ref, n: int):
    """The scalar elimination recurrence into SMEM scratch: cp/mi are
    the per-row back-substitution and normalization scalars of the
    constant-coefficient CN matrix (identity boundary rows). Runs once
    per program — O(n) scalar work against O(n*w) vector work."""
    c = s_ref[0, 0, 0]
    a = -0.5 * c
    b = 1.0 + c
    cp_ref[0] = jnp.zeros((), cp_ref.dtype)
    mi_ref[0] = jnp.ones((), mi_ref.dtype)

    def coeff(i, _):
        interior = jnp.logical_and(i >= 1, i <= n - 2)
        ai = jnp.where(interior, a, 0.0)
        bi = jnp.where(interior, b, 1.0)
        m = bi - ai * cp_ref[i - 1]
        mi_ref[i] = 1.0 / m
        cp_ref[i] = jnp.where(interior, a, 0.0) / m
        return 0

    lax.fori_loop(1, n, coeff, 0, unroll=False)
    return a


def _tridiag_rows_kernel(s_ref, rhs_ref, out_ref, cp_ref, mi_ref, *, n):
    """Solve along axis 0 (sublanes) of one member's (n, w) lane
    panel: every lane an independent system. Forward sweep and back
    substitution walk rows sequentially; each row op is a (1, w)
    vector — the lane axis carries the batch parallelism."""
    a = _coeff_loops(s_ref, cp_ref, mi_ref, n)
    out_ref[0, 0, :] = rhs_ref[0, 0, :]

    def fwd(i, _):
        ai = jnp.where(jnp.logical_and(i >= 1, i <= n - 2), a, 0.0)
        prev = out_ref[0, pl.ds(i - 1, 1), :]
        out_ref[0, pl.ds(i, 1), :] = (
            rhs_ref[0, pl.ds(i, 1), :] - ai * prev) * mi_ref[i]
        return 0

    lax.fori_loop(1, n, fwd, 0, unroll=False)

    def back(k, _):
        i = n - 2 - k
        nxt = out_ref[0, pl.ds(i + 1, 1), :]
        out_ref[0, pl.ds(i, 1), :] = (
            out_ref[0, pl.ds(i, 1), :] - cp_ref[i] * nxt)
        return 0

    lax.fori_loop(0, n - 1, back, 0, unroll=False)


def _tridiag_lanes_kernel(s_ref, rhs_ref, out_ref, cp_ref, mi_ref, *, n):
    """The STRIDED second pass: solve along axis 1 (lanes) of one
    member's (h, n) row panel — every sublane row an independent
    system, elimination marching across lanes. Lane-serial by
    construction (each op touches an (h, 1) column): the honest
    no-transpose alternative the tune space measures against
    ``variant="xpose"``."""
    a = _coeff_loops(s_ref, cp_ref, mi_ref, n)
    out_ref[0, :, pl.ds(0, 1)] = rhs_ref[0, :, pl.ds(0, 1)]

    def fwd(j, _):
        aj = jnp.where(jnp.logical_and(j >= 1, j <= n - 2), a, 0.0)
        prev = out_ref[0, :, pl.ds(j - 1, 1)]
        out_ref[0, :, pl.ds(j, 1)] = (
            rhs_ref[0, :, pl.ds(j, 1)] - aj * prev) * mi_ref[j]
        return 0

    lax.fori_loop(1, n, fwd, 0, unroll=False)

    def back(k, _):
        j = n - 2 - k
        nxt = out_ref[0, :, pl.ds(j + 1, 1)]
        out_ref[0, :, pl.ds(j, 1)] = (
            out_ref[0, :, pl.ds(j, 1)] - cp_ref[j] * nxt)
        return 0

    lax.fori_loop(0, n - 1, back, 0, unroll=False)


def plan_adi_panel(ny: int, panel: int | None = None) -> int:
    """Lane-panel width for the row-solve kernel: the largest divisor
    of ``ny`` that is <= the target and lane-aligned when possible —
    panels partition the lane axis exactly (no pad lanes to firewall:
    every lane is a real system)."""
    # ``panel`` is a static host-side knob (the tune space's bm axis),
    # never a traced value.
    target = DEFAULT_PANEL if panel is None else panel
    if target >= ny or ny <= 0:
        return ny
    for w in range(min(target, ny), 0, -1):
        if ny % w == 0 and (w % 128 == 0 or w == ny or ny % 128):
            return w
    return ny


def _solve_rows(scal, rhs, bn: int):
    """Batched x-solve: grid (B, ny/bn) over members x lane panels,
    each program solving its panel's systems along axis 0."""
    from heat2d_tpu.ops.pallas_stencil import (_interpret, _mem_spaces,
                                               _parallel_grid)

    b, n, ny = rhs.shape
    npan = ny // bn
    mspace, smem = _mem_spaces()
    return pl.pallas_call(
        functools.partial(_tridiag_rows_kernel, n=n),
        out_shape=jax.ShapeDtypeStruct(rhs.shape, rhs.dtype),
        grid=(b, npan),
        in_specs=[
            pl.BlockSpec((1, 1, 1), lambda i, j: (i, 0, 0), **smem),
            pl.BlockSpec((1, n, bn), lambda i, j: (i, 0, j), **mspace),
        ],
        out_specs=pl.BlockSpec((1, n, bn), lambda i, j: (i, 0, j),
                               **mspace),
        scratch_shapes=[_smem_scratch(n, rhs.dtype),
                        _smem_scratch(n, rhs.dtype)],
        interpret=_interpret(),
        **_parallel_grid(2))(scal, rhs)


def _solve_lanes(scal, rhs, bp: int):
    """Batched strided y-solve: grid (B, nx/bp) over members x row
    panels, each program eliminating along the full lane axis."""
    from heat2d_tpu.ops.pallas_stencil import (_interpret, _mem_spaces,
                                               _parallel_grid)

    b, nx, n = rhs.shape
    npan = nx // bp
    mspace, smem = _mem_spaces()
    return pl.pallas_call(
        functools.partial(_tridiag_lanes_kernel, n=n),
        out_shape=jax.ShapeDtypeStruct(rhs.shape, rhs.dtype),
        grid=(b, npan),
        in_specs=[
            pl.BlockSpec((1, 1, 1), lambda i, j: (i, 0, 0), **smem),
            pl.BlockSpec((1, bp, n), lambda i, j: (i, j, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((1, bp, n), lambda i, j: (i, j, 0),
                               **mspace),
        scratch_shapes=[_smem_scratch(n, rhs.dtype),
                        _smem_scratch(n, rhs.dtype)],
        interpret=_interpret(),
        **_parallel_grid(2))(scal, rhs)


def _smem_scratch(n: int, dtype):
    """(n,) scalar scratch for the elimination recurrence — SMEM on
    the chip; the interpreter allocates a host buffer either way."""
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.SMEM((n,), dtype)


def adi_kernel_viable(nx: int, ny: int, dtype=jnp.float32) -> bool:
    """Gate for the Pallas TD route on a REAL TPU backend: f32,
    lane-aligned width, and the member resident in VMEM (the
    band-streamed tridiag is future work — off-envelope shapes keep
    the scan route, which is correct everywhere)."""
    from heat2d_tpu.ops import pallas_stencil as ps

    return (ps._on_tpu() and jnp.dtype(dtype) == jnp.float32
            and ny % 128 == 0 and nx % 8 == 0
            and ps.fits_vmem((nx, ny), dtype))


def adi_sweep_kernel(u, cxs, cys, *, panel=None, variant="xpose"):
    """One batched ADI step through kernel TD. ``u`` is (B, nx, ny);
    ``cxs``/``cys`` per-member diffusion numbers. ``variant`` picks
    the second pass: "xpose" (explicit transpose + row kernel) or
    "strided" (lane-elimination kernel, no transpose)."""
    if variant not in VARIANTS:
        raise ValueError(
            f"variant must be one of {VARIANTS}, got {variant!r}")
    b, nx, ny = u.shape
    cb = jnp.reshape(jnp.asarray(cxs, u.dtype), (b, 1, 1))
    db_ = jnp.reshape(jnp.asarray(cys, u.dtype), (b, 1, 1))
    bn = plan_adi_panel(ny, panel)
    rhs1 = _rhs_half(u, db_, 1)
    ustar = _hold_edges(_solve_rows(cb, rhs1, bn), u)
    rhs2 = _rhs_half(ustar, cb, 0)
    if variant == "xpose":
        bp = plan_adi_panel(nx, panel)
        u1 = _solve_rows(db_, jnp.swapaxes(rhs2, 1, 2), bp)
        u1 = jnp.swapaxes(u1, 1, 2)
    else:
        bp = plan_adi_panel(nx, panel)
        u1 = _solve_lanes(db_, rhs2, bp)
    return _hold_edges(u1, u)


# --------------------------------------------------------------------- #
# batched multi-step entries (the ensemble runners' building blocks)
# --------------------------------------------------------------------- #

def batched_adi_scan(u0, cxs, cys, *, steps: int):
    """(B, nx, ny) batch advanced ``steps`` ADI steps through the jnp
    scan route (vmapped per member) — correct on every backend/dtype;
    the serve route off the kernel envelope and the diff primal."""
    if steps == 0:
        return u0
    cxs = jnp.asarray(cxs, u0.dtype)
    cys = jnp.asarray(cys, u0.dtype)

    def one(u, cx, cy):
        return adi_multi_step(u, steps, cx, cy)

    return jax.vmap(one)(u0, cxs, cys)


def batched_adi_kernel(u0, cxs, cys, *, steps: int, panel=None,
                       variant="xpose"):
    """Kernel-TD route: ``steps`` batched sweeps, time loop outside
    the kernel (each step is 2 tridiagonal launches + the elementwise
    half-RHS stencils, which XLA fuses around them)."""
    if steps == 0:
        return u0
    cxs = jnp.asarray(cxs, u0.dtype)
    cys = jnp.asarray(cys, u0.dtype)
    return lax.fori_loop(
        0, steps,
        lambda _, v: adi_sweep_kernel(v, cxs, cys, panel=panel,
                                      variant=variant),
        u0, unroll=False)
