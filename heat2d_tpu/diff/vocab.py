"""The diff subsystem's shared vocabulary — ONE definition each.

jax-free on purpose: ``serving.py`` validates requests on the
admission path and ``inverse.py`` is importable without jax; both need
these tuples, and ``adjoint.py`` (jax-heavy) is the wrong place to
make them import from.
"""

from heat2d_tpu import vocab as _vocab

#: coefficient forms of the differentiable solve
COEFFS = ("const", "var")

#: reverse-mode storage strategies
ADJOINTS = ("checkpoint", "full")

#: primal multi-step routes ("adi": the implicit Crank-Nicolson ADI
#: step — different MATH, not just a different kernel; its adjoint
#: rides the implicit differentiation of the tridiagonal solves,
#: ops/tridiag.thomas_solve's custom_vjp). Derived from the
#: single-source method vocabulary (heat2d_tpu/vocab.py) by excluding
#: the non-differentiable routes — this list, config.TIME_METHODS,
#: and serve.schema.SUPPORTED_METHODS share one set of atoms.
METHODS = _vocab.DIFF_METHODS

#: inverse-problem recovery targets
TARGETS = ("init", "diffusivity")
