"""The differentiable forward operator — checkpointed-segment adjoint.

``jax.grad`` of a plain ``lax.fori_loop`` forward converts the loop to
a scan and stores every one of the T step states for the backward pass
— O(T) device memory, which is exactly what makes long-horizon adjoints
infeasible on big grids. This module overrides reverse-mode with a
``custom_vjp`` whose storage is a *choice*:

- ``adjoint="checkpoint"`` (default): the forward stashes only every
  K-th state (the segment starts — K defaults to ~sqrt(T), the
  memory-optimal single-level schedule); the backward sweep walks the
  segments in reverse, RECOMPUTES each segment's K intermediate states
  from its stored start (one extra forward pass in total), then pulls
  the cotangent back step by step. Memory O(T/K + K), compute ~2x
  forward.
- ``adjoint="full"``: the reference — store all T states
  (``models.engine.run_fixed_stacked``), recompute nothing. Memory
  O(T), compute ~1x. The backward sweep walks the SAME segment
  schedule slicing the stored trajectory, so for the jnp step route
  the two adjoints are bitwise-identical gradient for gradient (the
  checkpointed recompute is deterministic) — tests pin this.

Both routes differentiate with respect to the initial state AND the
coefficients. Two coefficient forms:

- ``coeff="const"``: scalar (cx, cy) — the reference's diffusivities as
  traced operands.
- ``coeff="var"``: per-cell (kx, ky) fields (``ops.stencil_step_var``)
  — the heterogeneous-material route the inverse driver recovers.

Primal fusion: the forward (and the stored checkpoints) advance through
``method="jnp"`` (per-step ``lax.fori_loop``) or ``method="band"`` (the
batched temporally-blocked band kernel, B=1 — constant coefficients
only). The band route plans through ``ops._resolve_bands`` and
therefore consults the tuning db (``HEAT2D_TUNE_DB``) exactly like the
serve warm path; its FMA step form deviates from the jnp literal form
at f32-ulp, so the bitwise full-vs-checkpoint guarantee is pinned on
the jnp route (docs/DIFFERENTIABLE.md). ``method="auto"`` picks band
only on a real TPU backend for HBM-sized grids.

The per-step pullback uses ``jax.vjp`` of the SAME step function the
forward ran, linearized at the stored/recomputed state, so gradient
parity against central finite differences holds to f32 tolerance on
both coefficient routes (tests/test_diff.py).
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from heat2d_tpu.diff.vocab import ADJOINTS, COEFFS, METHODS
from heat2d_tpu.models.engine import run_fixed_stacked
from heat2d_tpu.ops.stencil import stencil_step, stencil_step_var


def segment_schedule(steps: int, segment=None) -> tuple:
    """The checkpointed-segment schedule: ``steps`` split into segment
    lengths (full segments of ``segment`` steps plus one remainder).
    ``segment=None`` picks ~sqrt(steps), minimizing stored + recomputed
    states for the single-level scheme. Returns a tuple summing to
    ``steps`` (empty for steps=0)."""
    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    if steps == 0:
        return ()
    if segment is None:
        segment = max(1, int(round(math.sqrt(steps))))
    segment = int(segment)
    if segment < 1:
        raise ValueError(f"segment must be >= 1, got {segment}")
    n_full, rem = divmod(steps, segment)
    return (segment,) * n_full + ((rem,) if rem else ())


@dataclasses.dataclass(frozen=True)
class DiffSpec:
    """Static spec of one differentiable solve — hashable, so it rides
    as a ``custom_vjp`` nondiff argument and keys jit caches."""
    nx: int
    ny: int
    steps: int
    coeff: str = "const"          # "const" (scalar cx, cy) | "var" (fields)
    adjoint: str = "checkpoint"   # "checkpoint" | "full"
    schedule: tuple = ()          # segment lengths (sum == steps)
    method: str = "jnp"           # primal multi-step route (resolved)


# --------------------------------------------------------------------- #
# step / multi-step primitives
# --------------------------------------------------------------------- #

def _step(spec: DiffSpec, u, a, b):
    """One forward step. ``accum_dtype=None``: accumulate in u's dtype
    (pure-f32 fast path; true f64 under x64 instead of a silent
    truncation through float32). ``method="adi"`` swaps the MATH for
    the implicit Crank-Nicolson ADI step (ops/tridiag.py): the
    per-step pullback below (``jax.vjp`` of this step) then rides the
    implicit differentiation of the tridiagonal solves — the backward
    pass solves the TRANSPOSE system (thomas_solve's custom_vjp),
    never an unrolled elimination trace. FD-parity-tested like every
    other route (tests/test_implicit.py)."""
    if spec.method == "adi":
        from heat2d_tpu.ops.tridiag import adi_step
        return adi_step(u, a, b)
    if spec.coeff == "const":
        return stencil_step(u, a, b, accum_dtype=None)
    return stencil_step_var(u, a, b)


def _multi(spec: DiffSpec, u, a, b, n: int):
    """Advance ``n`` steps WITHOUT storing intermediates — the fused
    primal. The band route runs the batched temporally-blocked kernel
    (B=1; (cx, cy) ride as traced SMEM scalars) and consults the
    tuning db through ``ops._resolve_bands`` like every band plan."""
    if n == 0:
        return u
    if spec.method == "band" and spec.coeff == "const":
        from heat2d_tpu.models.ensemble import _run_batch_band
        dt = u.dtype
        return _run_batch_band(
            u[None], jnp.reshape(jnp.asarray(a, dt), (1,)),
            jnp.reshape(jnp.asarray(b, dt), (1,)), steps=n)[0]
    return lax.fori_loop(0, n, lambda _, v: _step(spec, v, a, b), u,
                         unroll=False)


def _segment_states(spec: DiffSpec, u, a, b, n: int):
    """(u_after_n, states): the recompute primitive — ``states[t]`` is
    the input of step t (``states[0] == u``). One scan of the SAME step
    the full-storage forward runs, so recomputed states are bitwise the
    stored ones."""
    return run_fixed_stacked(lambda v: _step(spec, v, a, b), u, n)


# --------------------------------------------------------------------- #
# the custom-VJP operator
# --------------------------------------------------------------------- #

@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _diff_solve(spec: DiffSpec, u0, a, b):
    # Primal (autodiff unused): the fused forward only — no residual
    # storage, no checkpoint stash. jit of this path costs exactly the
    # fused multi-step.
    return _multi(spec, u0, a, b, spec.steps)


def _diff_solve_fwd(spec: DiffSpec, u0, a, b):
    if spec.adjoint == "full":
        u_final, states = _segment_states(spec, u0, a, b, spec.steps)
        return u_final, (states, a, b)
    # Checkpointed: stash only the segment-start states (every K-th).
    ck = [u0]
    u = u0
    for k in spec.schedule[:-1]:
        u = _multi(spec, u, a, b, k)
        ck.append(u)
    u_final = (_multi(spec, u, a, b, spec.schedule[-1])
               if spec.schedule else u)
    return u_final, (jnp.stack(ck), a, b)


def _diff_solve_bwd(spec: DiffSpec, res, wbar):
    stored, a, b = res
    ga = jnp.zeros_like(a)
    gb = jnp.zeros_like(b)

    def step_for_vjp(u, aa, bb):
        return _step(spec, u, aa, bb)

    def pullback(carry, u_t):
        w, ga, gb = carry
        _, vjp = jax.vjp(step_for_vjp, u_t, a, b)
        du, da, db = vjp(w)
        return (du, ga + da, gb + db), None

    carry = (wbar, ga, gb)
    starts = []
    t = 0
    for k in spec.schedule:
        starts.append(t)
        t += k
    for i in reversed(range(len(spec.schedule))):
        n = spec.schedule[i]
        if spec.adjoint == "full":
            seg_states = stored[starts[i]:starts[i] + n]
        else:
            # Recompute this segment's states from its stored start —
            # the same scan the full-storage forward ran, so the
            # linearization points are bitwise identical.
            _, seg_states = _segment_states(spec, stored[i], a, b, n)
        carry, _ = lax.scan(pullback, carry, seg_states, reverse=True)
    du0, ga, gb = carry
    return du0, ga, gb


_diff_solve.defvjp(_diff_solve_fwd, _diff_solve_bwd)


# --------------------------------------------------------------------- #
# public entry
# --------------------------------------------------------------------- #

def _resolve_method(method: str, nx: int, ny: int, coeff: str,
                    adjoint: str) -> str:
    if method not in METHODS:
        raise ValueError(f"method must be one of {METHODS}, got {method!r}")
    if coeff == "var":
        if method in ("band", "adi"):
            raise ValueError(
                f"method={method!r} supports coeff='const' only (the "
                "band/tridiagonal kernels take scalar diffusivities; "
                "the variable-coefficient route runs the jnp step)")
        return "jnp"
    if method == "adi":
        # The ADI primal is per-step on both adjoint routes (no fused
        # band form), so full storage and checkpointing both compose.
        return "adi"
    if adjoint == "full":
        # Full storage records EVERY step state — its forward is
        # necessarily the per-step scan, and custom_vjp's fwd must
        # reproduce the primal bit for bit, so the fused band primal
        # (FMA step form, f32-ulp off the scan) is not composable
        # with it. The fused primal is the checkpointed adjoint's
        # domain; full storage is the per-step reference.
        if method == "band":
            raise ValueError(
                "adjoint='full' records every step state (per-step "
                "scan); it cannot run the fused band primal — use "
                "adjoint='checkpoint' with method='band', or "
                "method='jnp'")
        return "jnp"
    if method != "auto":
        return method
    from heat2d_tpu.ops.pallas_stencil import _on_tpu, fits_vmem
    if _on_tpu() and not fits_vmem((nx, ny)):
        return "band"
    return "jnp"


def make_diff_solve(nx: int, ny: int, steps: int, *, coeff: str = "const",
                    adjoint: str = "checkpoint", segment=None,
                    method: str = "auto"):
    """Build the differentiable solve ``f(u0, a, b) -> u_final``.

    ``u0`` is the (nx, ny) initial grid; ``(a, b)`` are scalar
    ``(cx, cy)`` for ``coeff="const"`` or per-cell ``(kx, ky)`` fields
    for ``coeff="var"``. The returned callable is differentiable in all
    three arguments (``jax.grad``/``jax.vjp``/``jax.jit`` compose), and
    its reverse-mode memory follows ``adjoint``/``segment`` — see the
    module docstring and docs/DIFFERENTIABLE.md.
    """
    if nx < 3 or ny < 3:
        raise ValueError(f"grid must be at least 3x3, got {nx}x{ny}")
    if coeff not in COEFFS:
        raise ValueError(f"coeff must be one of {COEFFS}, got {coeff!r}")
    if adjoint not in ADJOINTS:
        raise ValueError(
            f"adjoint must be one of {ADJOINTS}, got {adjoint!r}")
    spec = DiffSpec(nx=int(nx), ny=int(ny), steps=int(steps), coeff=coeff,
                    adjoint=adjoint,
                    schedule=segment_schedule(steps, segment),
                    method=_resolve_method(method, nx, ny, coeff,
                                           adjoint))
    if spec.method == "band":
        # Pre-resolve the tuning db's answer for the fused segments
        # (the band plan consults it again at trace time through
        # ops._resolve_bands) so applied-config provenance reaches run
        # records before the first compile — the serve engine's
        # _preresolve_tuned pattern.
        from heat2d_tpu.tune import runtime as tune_runtime
        tune_runtime.adjoint_config(nx, ny)

    def solve(u0, a, b):
        u0 = jnp.asarray(u0)
        if u0.shape != (spec.nx, spec.ny):
            raise ValueError(
                f"u0 must be ({spec.nx}, {spec.ny}), got {u0.shape}")
        a = jnp.asarray(a, u0.dtype)
        b = jnp.asarray(b, u0.dtype)
        want = () if spec.coeff == "const" else (spec.nx, spec.ny)
        if a.shape != want or b.shape != want:
            raise ValueError(
                f"coeff={spec.coeff!r} takes coefficient shape {want}, "
                f"got {a.shape}/{b.shape}")
        return _diff_solve(spec, u0, a, b)

    solve.spec = spec
    return solve
