"""``heat2d-tpu-inverse`` — the inverse-problem workload driver.

Two modes:

- ``--selftest``: the CI smoke (CPU by default). Builds a known
  synthetic per-cell diffusivity field, generates final-state
  observations by running the variable-coefficient forward solve,
  submits the recovery as an ``InverseRequest`` through a REAL running
  ``SolveServer`` (batcher, cache, admission — the whole serving
  path), and asserts: the optimization converges below the loss
  threshold, the recovered field beats the initial guess by 10x, a
  repeat submission is a cache hit with identical loss, the
  checkpointed-segment adjoint matches the full-storage adjoint
  bitwise, and the per-iteration telemetry landed in the registry.
  Exit 0 iff every check holds.
- direct mode: one inverse solve from flags — observations either
  synthetic (``--observe-every``; the target field is the same
  synthetic bump the selftest uses) or loaded from ``save_field``
  files (``--observations``/``--obs-mask``). The recovered field can
  be saved back with ``--save-recovered`` (digest-sidecar'd, loadable
  with ``io.load_field``).

``--metrics-out`` writes the run's telemetry as JSONL (registry events
+ snapshot + a ``kind="inverse"`` run record carrying iteration count
and final loss), the same envelope as every other CLI.
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-inverse",
        description="differentiable-solve inverse problems: recover an "
                    "initial condition or per-cell diffusivity field "
                    "from sparse observations (docs/DIFFERENTIABLE.md)")
    p.add_argument("--selftest", action="store_true",
                   help="recover a known synthetic diffusivity field "
                        "through a running SolveServer and verify the "
                        "differentiable-serving invariants (CPU unless "
                        "--platform tpu); exit nonzero on any failure")
    g = p.add_argument_group("problem")
    g.add_argument("--target", default="diffusivity",
                   choices=["init", "diffusivity"])
    g.add_argument("--nxprob", type=int, default=16)
    g.add_argument("--nyprob", type=int, default=16)
    g.add_argument("--steps", type=int, default=16)
    g.add_argument("--cx", type=float, default=0.1,
                   help="known x diffusivity (target=init)")
    g.add_argument("--cy", type=float, default=0.1,
                   help="known y diffusivity (target=init)")
    o = p.add_argument_group("optimization")
    o.add_argument("--iterations", type=int, default=300)
    o.add_argument("--lr", type=float, default=0.02)
    o.add_argument("--tol", type=float, default=None,
                   help="early-stop loss threshold (converged flag)")
    o.add_argument("--reg", type=float, default=0.0,
                   help="Tikhonov weight on the recovered field")
    o.add_argument("--adjoint", default="checkpoint",
                   choices=["checkpoint", "full"],
                   help="reverse-mode storage: checkpointed segments "
                        "(O(sqrt(T)) states) or full trajectory")
    o.add_argument("--segment", type=int, default=None,
                   help="checkpoint segment length K (default ~sqrt(T))")
    d = p.add_argument_group("observations")
    d.add_argument("--observe-every", type=int, default=1, metavar="N",
                   help="synthetic mode: observe every N-th interior "
                        "cell of the final state")
    d.add_argument("--observations", default=None, metavar="PATH",
                   help="observed final-state values (io.save_field "
                        "file); requires --obs-mask")
    d.add_argument("--obs-mask", default=None, metavar="PATH",
                   help="bool observation mask (io.save_field file)")
    d.add_argument("--save-recovered", default=None, metavar="PATH",
                   help="write the recovered field via io.save_field "
                        "(digest sidecar; loadable with load_field)")
    p.add_argument("--run-record", default=None,
                   help="path for the JSON run record")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write telemetry JSONL (events + snapshot + the "
                        "kind='inverse' run record)")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    p.add_argument("--log-level", default=None,
                   choices=["debug", "info", "warning", "error"])
    return p


def _apply_platform(args, default_cpu: bool) -> None:
    """An EXPLICIT --platform always wins (overwrites JAX_PLATFORMS,
    like the sibling CLIs); the selftest's cpu default only fills in
    when the environment doesn't choose."""
    if args.platform is not None:
        os.environ["JAX_PLATFORMS"] = args.platform
        platform = args.platform
    elif default_cpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        platform = os.environ["JAX_PLATFORMS"]
    else:
        return
    import jax
    jax.config.update("jax_platforms", platform)


def _interior_mean_abs_err(a, b):
    import numpy as np
    d = np.abs(np.asarray(a) - np.asarray(b))
    return float(d[1:-1, 1:-1].mean())


def run_selftest(args, registry) -> int:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from heat2d_tpu.diff.adjoint import make_diff_solve
    from heat2d_tpu.diff.inverse import (observation_mask,
                                         synthetic_diffusivity,
                                         unit_reference_init)
    from heat2d_tpu.diff.serving import InverseRequest
    from heat2d_tpu.serve.server import SolveServer

    failures = []
    nx, ny, steps = args.nxprob, args.nyprob, args.steps
    tol = args.tol if args.tol is not None else 1e-8

    # The known target and its observations.
    true_k = synthetic_diffusivity(nx, ny)
    u0 = unit_reference_init(nx, ny)
    fwd = make_diff_solve(nx, ny, steps, coeff="var")
    u_true = np.asarray(fwd(jnp.asarray(u0), jnp.asarray(true_k),
                            jnp.asarray(true_k)))
    mask = observation_mask(nx, ny, every=args.observe_every)
    req = InverseRequest.from_fields(
        nx, ny, steps, mask, u_true, target="diffusivity",
        iterations=args.iterations, lr=args.lr, tol=tol,
        adjoint=args.adjoint, segment=args.segment)

    # 1) End to end through the REAL serving path.
    server = SolveServer(registry=registry, max_delay=0.01)
    with server:
        res = server.solve(req, timeout=600)
        again = server.solve(req, timeout=600)
    if not res.converged or not res.final_loss <= tol:
        failures.append(f"did not converge below tol={tol:g}: "
                        f"loss={res.final_loss:g} after "
                        f"{res.iterations} iterations")
    if not again.cache_hit:
        failures.append("repeat submission was not a cache hit")
    if again.final_loss != res.final_loss:
        failures.append("cache hit returned a different loss")
    err0 = _interior_mean_abs_err(np.full((nx, ny), 0.1), true_k)
    err = _interior_mean_abs_err(res.params, true_k)
    if not err < 0.1 * err0:
        failures.append(f"recovered field error {err:g} not < 10% of "
                        f"initial-guess error {err0:g}")

    # 2) Adjoint invariant: checkpointed == full-storage, bitwise.
    w = jnp.asarray(np.random.RandomState(0)
                    .randn(nx, ny).astype(np.float32))
    uj = jnp.asarray(u0)
    for name, argnum in (("u0", 0), ("cx", 1)):
        g = []
        for adjoint in ("checkpoint", "full"):
            f = make_diff_solve(nx, ny, steps, adjoint=adjoint)
            g.append(np.asarray(jax.grad(
                lambda u, a, b: jnp.sum(w * f(u, a, b)),  # noqa: B023
                argnums=argnum)(uj, 0.1, 0.1)))
        if g[0].tobytes() != g[1].tobytes():
            failures.append(f"checkpointed adjoint grad w.r.t. {name} "
                            f"not bitwise-identical to full storage")

    # 3) Telemetry landed.
    snap = registry.snapshot()
    if not any(k.startswith("inverse_loss") for k in snap["series"]):
        failures.append("no inverse_loss series recorded")
    if snap["counters"].get("inverse_iterations_total", 0) < 1:
        failures.append("inverse_iterations_total not recorded")

    print(f"selftest: {res.iterations} iterations -> "
          f"loss {res.final_loss:.3e} (tol {tol:g}), field error "
          f"{err:.2e} (from {err0:.2e}), cache_hit={again.cache_hit}")
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    _write_outputs(args, registry, {
        "target": "diffusivity", "grid": f"{nx}x{ny}", "steps": steps,
        "iterations": res.iterations, "final_loss": res.final_loss,
        "converged": res.converged, "tol": tol,
        "field_error": err, "field_error_initial": err0,
        "cache_hit_repeat": again.cache_hit,
        "selftest_failures": failures})
    print("inverse selftest " + ("FAILED" if failures else "passed"))
    return 1 if failures else 0


def run_direct(args, registry) -> int:
    import numpy as np
    import jax.numpy as jnp

    from heat2d_tpu.diff.adjoint import make_diff_solve
    from heat2d_tpu.diff.inverse import (InverseProblem, observation_mask,
                                         synthetic_diffusivity,
                                         unit_reference_init)
    from heat2d_tpu.io.binary import (CheckpointCorruptError, load_field,
                                      save_field)

    nx, ny, steps = args.nxprob, args.nyprob, args.steps
    if (args.observations is None) != (args.obs_mask is None):
        print("--observations and --obs-mask go together\nQuitting...",
              file=sys.stderr)
        return 1
    true_k = None
    if args.observations is not None:
        try:
            values, _ = load_field(args.observations)
            mask, _ = load_field(args.obs_mask)
        except (CheckpointCorruptError, OSError, ValueError) as e:
            print(f"{e}\nQuitting...", file=sys.stderr)
            return 1
        mask = np.asarray(mask, bool)
        if mask.shape != (nx, ny) or values.shape != (nx, ny):
            print(f"observation files must be {nx}x{ny}, got "
                  f"{values.shape}/{mask.shape}\nQuitting...",
                  file=sys.stderr)
            return 1
    else:
        # Synthetic observations of the known bump field (or of the
        # reference init for target=init) — the demo/benchmark mode.
        u0 = unit_reference_init(nx, ny)
        if args.target == "diffusivity":
            true_k = synthetic_diffusivity(nx, ny)
            u_true = np.asarray(make_diff_solve(
                nx, ny, steps, coeff="var")(
                    jnp.asarray(u0), jnp.asarray(true_k),
                    jnp.asarray(true_k)))
        else:
            u_true = np.asarray(make_diff_solve(nx, ny, steps)(
                jnp.asarray(u0), args.cx, args.cy))
        mask = observation_mask(nx, ny, every=args.observe_every)
        values = u_true

    problem = InverseProblem(
        nx=nx, ny=ny, steps=steps, target=args.target,
        obs_mask=mask, obs_values=values, cx=args.cx, cy=args.cy,
        u0=(unit_reference_init(nx, ny)
            if args.target == "diffusivity" else None),
        reg=args.reg, adjoint=args.adjoint, segment=args.segment)
    sol = problem.solve(iterations=args.iterations, lr=args.lr,
                        tol=args.tol, registry=registry)

    print(f"Inverse ({args.target}) on {nx}x{ny}, {steps} steps: "
          f"{sol.iterations} iterations, final loss "
          f"{sol.final_loss:.6e}, grad norm {sol.grad_norm:.3e}"
          + (", converged" if sol.converged else ""))
    extra = {
        "target": args.target, "grid": f"{nx}x{ny}", "steps": steps,
        "iterations": sol.iterations, "final_loss": sol.final_loss,
        "converged": sol.converged, "grad_norm": sol.grad_norm,
        "n_observations": int(np.count_nonzero(mask)),
    }
    if true_k is not None:
        extra["field_error"] = _interior_mean_abs_err(sol.params, true_k)
        print(f"Recovered-field interior error vs known target: "
              f"{extra['field_error']:.3e}")
    if args.save_recovered:
        save_field(sol.params, args.save_recovered,
                   name=f"recovered_{args.target}",
                   extra={"final_loss": sol.final_loss,
                          "iterations": sol.iterations})
        print(f"Writing {args.save_recovered} ...")
    _write_outputs(args, registry, extra)
    return 0


def _write_outputs(args, registry, extra) -> None:
    from heat2d_tpu.obs.record import build_record, write_run_jsonl
    from heat2d_tpu.tune import runtime as tune_runtime

    extra = dict(extra)
    tuned = tune_runtime.applied_configs()
    if tuned:
        extra["tuned_config"] = tuned
    if registry is not None and args.metrics_out:
        # The shared one-line telemetry export (events + snapshot +
        # the kind="inverse" run record) every CLI uses.
        write_run_jsonl(registry, args.metrics_out, "inverse", extra)
    if args.run_record:
        from heat2d_tpu.io.binary import write_json_atomic
        write_json_atomic(build_record("inverse", extra=extra),
                          args.run_record)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.log_level:
        import logging
        logging.basicConfig(
            format="%(asctime)s %(name)s %(levelname)s %(message)s")
        logging.getLogger("heat2d_tpu").setLevel(
            getattr(logging, args.log_level.upper()))
    _apply_platform(args, default_cpu=args.selftest)

    from heat2d_tpu.obs import MetricsRegistry
    registry = MetricsRegistry()
    if args.selftest:
        return run_selftest(args, registry)
    return run_direct(args, registry)


if __name__ == "__main__":
    sys.exit(main())
