"""Differentiable solves & inverse problems (ROADMAP item 4).

This is a JAX codebase, and until this package nothing in it called
``jax.grad``: the forward 5-point Jacobi solve was served at 98% of the
single-chip bound, but only *forward*. This package exposes the solver
as a differentiable operator and ships the inverse-problem workload
that turns one forward solve into a whole request class (parameter
recovery, sensitivity analysis, data assimilation):

- ``adjoint`` — the differentiable forward operator: ``custom_vjp``
                over the fused multi-step path so reverse-mode never
                naively unrolls (and never stores) all T step states;
                a checkpointed-segment adjoint (store every K-th state,
                recompute segments on the backward sweep — O(T/K + K)
                memory) selectable against a full-storage reference
                adjoint (O(T) memory, zero recompute). Constant
                (cx, cy) and per-cell variable-coefficient
                (``ops.stencil_step_var``) routes.
- ``inverse`` — gradient-based recovery of an initial condition or a
                per-cell diffusivity field from sparse observations:
                Adam/GD on the differentiable solve, stability-box
                projection, per-iteration loss/grad-norm telemetry
                through the obs/ metrics registry.
- ``serving`` — ``InverseRequest``/``InverseResult``: optimization
                loops as first-class serving requests through the
                existing ``serve`` batcher/cache/admission (content-
                hashed like ``SolveRequest``; repeat submissions are
                cache hits, duplicates coalesce in flight).
- ``cli``     — ``heat2d-tpu-inverse`` (``--selftest`` recovers a known
                synthetic diffusivity field through a running
                SolveServer — the CI smoke job).

Zero cost when unused: importing this package (or building operators
from it) changes no existing traced program — the forward solver and
the serve batch runners stay byte-identical (jaxpr-pinned by
tests/test_diff.py), exactly the obs/chaos/tune contract.
"""

from heat2d_tpu.diff.inverse import (InverseProblem, InverseSolution,
                                     adam_minimize, observation_mask,
                                     synthetic_diffusivity,
                                     unit_reference_init)
from heat2d_tpu.diff.serving import (InverseEngine, InverseRequest,
                                     InverseResult)
from heat2d_tpu.diff.vocab import ADJOINTS, COEFFS, METHODS, TARGETS

#: adjoint.py is jax-heavy; everything above imports without jax, so a
#: client that only builds/hashes InverseRequests (the admission path
#: serving.py keeps jax-free) never pays the jax import. The adjoint
#: names resolve lazily on first access (PEP 562).
_ADJOINT_EXPORTS = ("DiffSpec", "make_diff_solve", "segment_schedule")


def __getattr__(name):
    if name in _ADJOINT_EXPORTS:
        from heat2d_tpu.diff import adjoint
        return getattr(adjoint, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "ADJOINTS",
    "COEFFS",
    "METHODS",
    "TARGETS",
    "DiffSpec",
    "InverseEngine",
    "InverseProblem",
    "InverseRequest",
    "InverseResult",
    "InverseSolution",
    "adam_minimize",
    "make_diff_solve",
    "observation_mask",
    "segment_schedule",
    "synthetic_diffusivity",
    "unit_reference_init",
]
