"""Optimization loops as first-class serving requests.

An ``InverseRequest`` rides the EXACT serving path a ``SolveRequest``
does — validated at the door, content-hashed into the result cache and
single-flight dedup, admitted through the micro-batcher's queue-depth
shedding and timeouts, dispatched under the retry/watchdog/breaker
plumbing — because the request duck-types the same serving protocol:

- ``request_kind = "inverse"`` routes the dispatched bucket to the
  ``InverseEngine`` instead of the ensemble engine (serve/server.py);
- ``content_hash()`` is sha256 over the canonical spec INCLUDING the
  observation data, so two requests coalesce/cache-hit iff they are
  the same inverse problem bit for bit;
- ``signature()`` buckets by compiled program + loop shape (grid,
  steps, target, adjoint schedule, iteration budget) — members of one
  bucket share ONE compiled value_and_grad through the memoized
  ``inverse.loss_grad_runner`` (observations ride as operands, so a
  bucket pays a single compile, like a solve bucket pays one launch).

Observations travel as parallel tuples of (flat row-major cell index,
observed value) — plain data, JSON-able, hashable; ``from_fields``
builds them from (mask, values) arrays and ``mask()``/``values()``
reconstruct the arrays. Everything outside ``InverseEngine`` stays
jax-free so admission-path hashing is as cheap as for solves.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import logging
from typing import ClassVar, Optional

import numpy as np

from heat2d_tpu.diff.vocab import ADJOINTS, TARGETS
from heat2d_tpu.serve.schema import Rejected

log = logging.getLogger("heat2d_tpu.diff")


@dataclasses.dataclass(frozen=True)
class InverseRequest:
    """One inverse solve: recover ``target`` from sparse final-state
    observations by ``iterations`` of Adam at rate ``lr`` on the
    differentiable solve. Frozen — the hash of an admitted request
    must not drift in the queue (same contract as SolveRequest)."""

    nx: int
    ny: int
    steps: int
    obs_indices: tuple          # flat row-major indices of observed cells
    obs_values: tuple           # observed values, parallel to obs_indices
    target: str = "diffusivity"
    iterations: int = 100
    lr: float = 0.05
    cx: float = 0.1             # known coefficients (target="init")
    cy: float = 0.1
    tol: Optional[float] = None  # early-stop loss threshold
    reg: float = 0.0
    adjoint: str = "checkpoint"
    segment: Optional[int] = None
    dtype: str = "float32"

    #: serving-protocol tag — serve/server.py routes dispatch on it
    request_kind: ClassVar[str] = "inverse"

    # -- construction helpers ------------------------------------------ #

    @classmethod
    def from_fields(cls, nx: int, ny: int, steps: int, mask, values,
                    **kw) -> "InverseRequest":
        """Build from (nx, ny) mask/values arrays (the inverse.py
        field form)."""
        mask = np.asarray(mask, bool)
        values = np.asarray(values, np.float32)
        if mask.shape != (nx, ny) or values.shape != (nx, ny):
            raise Rejected("invalid",
                           f"mask/values must be ({nx}, {ny}), got "
                           f"{mask.shape}/{values.shape}")
        idx = np.flatnonzero(mask.ravel())
        return cls(nx=nx, ny=ny, steps=steps,
                   obs_indices=tuple(int(i) for i in idx),
                   obs_values=tuple(float(v)
                                    for v in values.ravel()[idx]),
                   **kw).validate()

    def mask(self) -> np.ndarray:
        m = np.zeros(self.nx * self.ny, bool)
        m[list(self.obs_indices)] = True
        return m.reshape(self.nx, self.ny)

    def values(self) -> np.ndarray:
        v = np.zeros(self.nx * self.ny, np.float32)
        v[list(self.obs_indices)] = np.asarray(self.obs_values,
                                               np.float32)
        return v.reshape(self.nx, self.ny)

    # -- serving protocol ---------------------------------------------- #

    def validate(self) -> "InverseRequest":
        if self.nx < 3 or self.ny < 3:
            raise Rejected("invalid", f"grid must be at least 3x3, got "
                           f"{self.nx}x{self.ny}")
        if self.steps < 0:
            raise Rejected("invalid",
                           f"steps must be >= 0, got {self.steps}")
        if self.target not in TARGETS:
            raise Rejected("invalid", f"target {self.target!r} not in "
                           f"{TARGETS}")
        if self.adjoint not in ADJOINTS:
            raise Rejected("invalid", f"adjoint {self.adjoint!r} not in "
                           f"{ADJOINTS}")
        if self.iterations < 1:
            raise Rejected("invalid", f"iterations must be >= 1, got "
                           f"{self.iterations}")
        if not self.lr > 0:
            raise Rejected("invalid", f"lr must be > 0, got {self.lr}")
        if self.tol is not None and not self.tol > 0:
            raise Rejected("invalid",
                           f"tol must be > 0 or null, got {self.tol}")
        if self.segment is not None and self.segment < 1:
            raise Rejected("invalid", f"segment must be >= 1 or null, "
                           f"got {self.segment}")
        if self.dtype != "float32":
            raise Rejected("invalid", f"dtype {self.dtype!r} not in "
                           f"('float32',)")
        n = len(self.obs_indices)
        if n == 0 or n != len(self.obs_values):
            raise Rejected("invalid",
                           "obs_indices/obs_values must be non-empty "
                           f"equal-length tuples, got {n}/"
                           f"{len(self.obs_values)}")
        cells = self.nx * self.ny
        idx = list(self.obs_indices)
        if min(idx) < 0 or max(idx) >= cells or len(set(idx)) != n:
            raise Rejected("invalid",
                           f"obs_indices must be {n} distinct flat "
                           f"indices in [0, {cells})")
        return self

    def spec(self) -> dict:
        """Canonical spec dict — all hashed fields, fixed order.
        Observations included: the DATA is part of the computation's
        identity (two masks' worth of values must never share a cache
        entry)."""
        return {
            "kind": "inverse",
            "nx": int(self.nx), "ny": int(self.ny),
            "steps": int(self.steps),
            "target": self.target,
            "iterations": int(self.iterations),
            "lr": float(self.lr),
            "cx": float(self.cx), "cy": float(self.cy),
            "tol": None if self.tol is None else float(self.tol),
            "reg": float(self.reg),
            "adjoint": self.adjoint,
            "segment": None if self.segment is None else int(self.segment),
            "dtype": self.dtype,
            "obs_indices": [int(i) for i in self.obs_indices],
            "obs_values": [float(v) for v in self.obs_values],
        }

    def content_hash(self) -> str:
        # Memoized on the frozen instance: the spec JSON covers every
        # observation point, and the hash is consulted on admission AND
        # again at dispatch — O(n_obs) serialization must happen once.
        cached = getattr(self, "_content_hash", None)
        if cached is None:
            blob = json.dumps(self.spec(), sort_keys=True,
                              separators=(",", ":"))
            cached = hashlib.sha256(blob.encode()).hexdigest()
            object.__setattr__(self, "_content_hash", cached)
        return cached

    def signature(self) -> tuple:
        """The batch-bucket key: compiled-program + loop-shape fields.
        Observation data and (cx, cy, lr, tol, reg) vary within a
        bucket — they are operands/host-loop inputs of the shared
        jitted value_and_grad, not compile keys. The leading tag keeps
        inverse buckets disjoint from solve buckets."""
        return ("inverse", self.nx, self.ny, self.steps, self.target,
                self.iterations, self.adjoint,
                0 if self.segment is None else self.segment, self.dtype)

    @classmethod
    def from_dict(cls, d: dict) -> "InverseRequest":
        known = {f.name for f in dataclasses.fields(cls)}
        bad = set(d) - known
        if bad:
            raise Rejected("invalid",
                           f"unknown request fields: {sorted(bad)}")
        d = dict(d)
        for k in ("obs_indices", "obs_values"):
            if k in d:
                d[k] = tuple(d[k])
        try:
            return cls(**d).validate()
        except TypeError as e:
            raise Rejected("invalid", str(e)) from None


@dataclasses.dataclass
class InverseResult:
    """One served inverse solve. ``params`` is the recovered field
    (host numpy, best-loss iterate); the serving labels mirror
    SolveResult's."""

    params: "object"
    final_loss: float
    iterations: int
    converged: bool
    grad_norm: float
    content_hash: str
    cache_hit: bool = False
    coalesced: bool = False
    batch_size: int = 1
    loss_history: list = dataclasses.field(default_factory=list)

    def as_cache_hit(self) -> "InverseResult":
        return dataclasses.replace(self, cache_hit=True, coalesced=False)

    def summary(self) -> dict:
        p = np.asarray(self.params)
        return {
            "kind": "inverse",
            "content_hash": self.content_hash,
            "final_loss": float(self.final_loss),
            "iterations": int(self.iterations),
            "converged": bool(self.converged),
            "grad_norm": float(self.grad_norm),
            "cache_hit": bool(self.cache_hit),
            "coalesced": bool(self.coalesced),
            "batch_size": int(self.batch_size),
            "shape": list(p.shape),
            "params_min": float(p.min()),
            "params_max": float(p.max()),
            "params_mean": float(p.mean()),
        }


class InverseEngine:
    """Executes dispatched inverse buckets. One bucket -> the members'
    optimization loops run back to back on the inverse dispatch lane;
    members of a bucket share ONE compiled value_and_grad (the
    memoized ``inverse.loss_grad_runner`` — observations are operands,
    not closure constants), so the batch pays a single compile the way
    solve buckets pay a single launch. May raise transients (including
    the injected ``ChaosError`` via the same launch fault-injection
    point as solves) — the server's retry policy owns absorbing them.

    Boundedness: an optimization loop is long-lived host work, so the
    engine checks two host signals once per iteration and aborts with
    a structured ``Rejected`` — ``deadline`` (the server's
    ``launch_deadline``: the watchdog fails the waiters at the
    deadline, this abort frees the lane shortly after) and
    ``stop_event`` (a non-drain server stop interrupts mid-loop
    instead of holding shutdown for the full iteration budget).

    Metrics: ``inverse_solves_total{outcome}``, ``inverse_solve_s``
    histogram, plus the per-iteration ``inverse_loss`` /
    ``inverse_grad_norm`` series and ``inverse_iterations_total`` the
    optimizer streams (labeled by short content hash).
    """

    def __init__(self, registry=None, deadline=None, stop_event=None,
                 clock=None):
        self.registry = registry
        self.deadline = deadline
        self.stop_event = stop_event
        #: the clock the deadline reads (None = time.monotonic):
        #: injected by tests so the abort is driven deterministically
        #: instead of racing real compile time on slow hosts
        self.clock = clock
        self.solves = 0
        self.solve_log: list = []

    def _iteration_guard(self):
        import time
        clock = time.monotonic if self.clock is None else self.clock
        t0 = clock()

        def check(_it, _loss, _gn):
            if self.stop_event is not None and self.stop_event.is_set():
                raise Rejected("shutdown",
                               "server stopping mid-optimization")
            if self.deadline is not None \
                    and clock() - t0 > self.deadline:
                raise Rejected(
                    "watchdog_timeout",
                    f"inverse optimization exceeded the "
                    f"{self.deadline}s launch deadline")
        return check

    def solve_batch(self, requests) -> list:
        from heat2d_tpu.resil import chaos
        chaos.launch_point()

        from heat2d_tpu.diff.inverse import (InverseProblem,
                                             unit_reference_init)

        guard = self._iteration_guard()
        out = []
        for req in requests:
            key = req.content_hash()
            # Diffusivity recoveries run from the canonical unit-peak
            # reference init: the request carries no u0, so the known
            # initial condition must be a pure function of the spec
            # (anything else would break content-hash identity).
            u0 = (unit_reference_init(req.nx, req.ny)
                  if req.target == "diffusivity" else None)
            problem = InverseProblem(
                nx=req.nx, ny=req.ny, steps=req.steps, target=req.target,
                obs_mask=req.mask(), obs_values=req.values(),
                cx=req.cx, cy=req.cy, u0=u0, reg=req.reg,
                adjoint=req.adjoint, segment=req.segment)
            timer = (self.registry.timer("inverse_solve_s")
                     if self.registry is not None
                     else contextlib.nullcontext())
            with timer:
                sol = problem.solve(
                    iterations=req.iterations, lr=req.lr, tol=req.tol,
                    registry=self.registry,
                    series_labels={"hash": key[:12]}, progress=guard)
            self.solves += 1
            self.solve_log.append({
                "signature": req.signature(), "content_hash": key,
                "iterations": sol.iterations,
                "final_loss": sol.final_loss,
                "converged": sol.converged})
            if self.registry is not None:
                self.registry.counter(
                    "inverse_solves_total",
                    outcome="converged" if sol.converged else "budget")
            log.debug("inverse solve %d: %dx%d target=%s iters=%d "
                      "loss=%.3e", self.solves, req.nx, req.ny,
                      req.target, sol.iterations, sol.final_loss)
            out.append(InverseResult(
                params=sol.params, final_loss=sol.final_loss,
                iterations=sol.iterations, converged=sol.converged,
                grad_norm=sol.grad_norm, content_hash=key,
                loss_history=sol.loss_history))
        return out
