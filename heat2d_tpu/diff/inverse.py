"""Inverse-problem driver — recover fields from sparse observations.

The reference pipeline (and every PR before this one) runs the heat
equation *forward*: coefficients in, final temperature out. This module
runs it backward: given sparse observations of the final state, recover
either

- ``target="init"``        — the initial condition ``u0`` (the known
                             (cx, cy) constant-coefficient route), or
- ``target="diffusivity"`` — a per-cell isotropic diffusivity field
                             ``kappa`` (``kx = ky = kappa``, the
                             variable-coefficient route of
                             ``ops.stencil_step_var``),

by Adam (or plain gradient descent) on the differentiable solve
(``diff.adjoint.make_diff_solve``). The loss is the mean squared
mismatch over observed cells, optionally Tikhonov-regularized; the
diffusivity route projects every iterate into the explicit-scheme
stability box ``kappa in [k_min, 0.24]`` (``kx + ky <= 1/2``).

Telemetry: every iteration streams ``inverse_loss`` and
``inverse_grad_norm`` series points plus an ``inverse_iterations_total``
counter through the obs/ metrics registry (docs/OBSERVABILITY.md) — the
optimization trajectory is first-class observable exactly like the
convergence-residual trajectory of a forward solve.

The optimizer is a host loop over one jitted ``value_and_grad`` — the
per-iteration solve+adjoint is one compiled program (compiled once per
signature), and the host only sees two scalars per iteration plus the
final field. Best-so-far parameters are tracked as HOST copies via
``resil.snapshot_state`` (the same snapshot primitive the async
checkpointer uses), so a diverging tail never loses the best iterate.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional

import numpy as np

from heat2d_tpu.diff.vocab import TARGETS
# The stability box now lives in ops/stability.py (ONE home for the
# kx + ky <= 1/2 projection — PR 14's factoring); re-exported here
# for back-compat with every existing import site.
from heat2d_tpu.ops.stability import KAPPA_MIN, KAPPA_MAX  # noqa: F401


def synthetic_diffusivity(nx: int, ny: int, base: float = 0.08,
                          bump: float = 0.08) -> np.ndarray:
    """A smooth known kappa field for selftests/CI: ``base`` plus an
    off-center Gaussian bump of height ``bump``, everywhere inside the
    stability box. The recovery target of ``--selftest``."""
    ix = np.arange(nx, dtype=np.float32)[:, None]
    iy = np.arange(ny, dtype=np.float32)[None, :]
    gx = np.exp(-((ix - nx / 3.0) ** 2) / (2 * (nx / 6.0) ** 2))
    gy = np.exp(-((iy - 2 * ny / 3.0) ** 2) / (2 * (ny / 6.0) ** 2))
    return (base + bump * gx * gy).astype(np.float32)


def unit_reference_init(nx: int, ny: int) -> np.ndarray:
    """The reference initial condition (``ops.init.inidat``) scaled to
    unit peak — the canonical KNOWN init of serving-path diffusivity
    recoveries (``diff.serving.InverseEngine``). The raw inidat peaks
    at ~(nx·ny/4)² and squares into the loss; unit peak keeps losses
    O(1) so request-level ``tol`` thresholds mean the same thing at
    every grid size."""
    from heat2d_tpu.ops.init import inidat
    u0 = np.asarray(inidat(nx, ny))
    return (u0 / u0.max()).astype(np.float32)


def observation_mask(nx: int, ny: int, every: int = 3) -> np.ndarray:
    """Sparse interior observation mask: every ``every``-th interior
    cell (edges are boundary-held and carry no information)."""
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    m = np.zeros((nx, ny), dtype=bool)
    m[1:-1:every, 1:-1:every] = True
    return m


@functools.lru_cache(maxsize=64)
def loss_grad_runner(nx: int, ny: int, steps: int, target: str,
                     adjoint: str, segment: Optional[int], method: str,
                     reg_on: bool) -> Callable:
    """The per-COMPILE-SIGNATURE memoized ``jax.jit(value_and_grad)``
    of the observation-mismatch loss — the inverse analogue of
    ``models.ensemble.batch_runner``. Everything problem-specific that
    does NOT change the traced program rides as operands:

    ``runner(params, *, aux, mask, obs, n_obs, reg) -> (loss, grad)``

    where ``aux`` is ``(cx, cy)`` scalars for ``target="init"`` (params
    is the candidate u0) or ``(u0,)`` for ``target="diffusivity"``
    (params is the candidate kappa field). ``reg_on`` is a static key:
    with regularization off the traced program carries no dead
    regularization term."""
    import jax
    import jax.numpy as jnp

    from heat2d_tpu.diff.adjoint import make_diff_solve

    coeff = "const" if target == "init" else "var"
    solve = make_diff_solve(nx, ny, steps, coeff=coeff, adjoint=adjoint,
                            segment=segment, method=method)

    def loss(params, aux, mask, obs, n_obs, reg):
        if target == "init":
            u = solve(params, aux[0], aux[1])
        else:
            u = solve(aux[0], params, params)
        r = (u - obs) * mask
        out = jnp.sum(r * r) / n_obs
        if reg_on:
            out = out + reg * jnp.mean(params * params)
        return out

    return jax.jit(jax.value_and_grad(loss))


@dataclasses.dataclass
class AdamState:
    """The optimizer's complete state between two iterations — the
    live-migration checkpoint (``autoscale/migrate.py``). Everything
    is a HOST copy (``resil.snapshot_state(dtype=None)``: exact, no
    dtype truncation), so the state round-trips bitwise through
    serialization, and the host Adam update — a deterministic pure
    function of (params, m, v, it) driven by the memoized compiled
    ``value_and_grad`` — makes a resumed run bitwise-identical to an
    uninterrupted one. ``iteration`` counts COMPLETED iterations: the
    bias corrections ``1 - beta**it`` depend on the absolute index,
    which is why it rides in the state instead of restarting at 0."""
    iteration: int
    params: np.ndarray
    m: np.ndarray
    v: np.ndarray
    best: np.ndarray
    best_loss: float
    loss_history: list
    grad_norm_history: list


@dataclasses.dataclass
class InverseSolution:
    """One finished inverse solve. ``params`` is the best-loss iterate
    (host numpy), not necessarily the last. A PAUSED solve (the
    live-migration checkpoint path) sets ``paused`` and carries the
    resumable ``state`` instead of claiming convergence."""
    params: np.ndarray
    final_loss: float
    iterations: int
    converged: bool
    grad_norm: float
    loss_history: list
    grad_norm_history: list
    paused: bool = False
    state: Optional[AdamState] = None


def adam_minimize(value_and_grad: Callable, params0, *,
                  iterations: int = 100, lr: float = 0.05,
                  beta1: float = 0.9, beta2: float = 0.999,
                  eps: float = 1e-8, project: Optional[Callable] = None,
                  tol: Optional[float] = None, registry=None,
                  series_labels: Optional[dict] = None,
                  progress: Optional[Callable] = None,
                  state: Optional[AdamState] = None,
                  pause: Optional[Callable[[int], bool]] = None
                  ) -> InverseSolution:
    """Adam with optional projection, early stop, and pause/resume.

    ``value_and_grad(params) -> (loss, grad)`` (typically jitted);
    ``project(params) -> params`` clamps each iterate (stability box);
    ``tol`` stops early once ``loss <= tol`` (sets ``converged``);
    ``registry``/``series_labels`` stream the per-iteration
    ``inverse_loss`` / ``inverse_grad_norm`` series; ``progress`` is an
    optional host callback ``(iteration, loss, grad_norm)``.

    ``pause(completed_iterations) -> bool`` is polled at each iteration
    BOUNDARY (never mid-update): when it turns truthy the solve returns
    ``paused=True`` with an ``AdamState`` checkpoint instead of a
    verdict. ``state`` resumes from such a checkpoint; ``iterations``
    stays the TOTAL budget, and the resumed trajectory is
    bitwise-identical to an uninterrupted run (AdamState docstring)."""
    import jax.numpy as jnp

    from heat2d_tpu.resil.snapshot import snapshot_state

    if iterations < 1:
        raise ValueError(f"iterations must be >= 1, got {iterations}")
    labels = dict(series_labels or {})
    if state is None:
        params = jnp.asarray(params0)
        m = jnp.zeros_like(params)
        v = jnp.zeros_like(params)
        loss_hist: list = []
        gn_hist: list = []
        best_loss = float("inf")
        # dtype=None: the snapshot keeps the optimization's dtype — an
        # f64 run's best iterate must not truncate through float32.
        best = snapshot_state(params, dtype=None)
        it = 0
    else:
        params = jnp.asarray(state.params)
        m = jnp.asarray(state.m)
        v = jnp.asarray(state.v)
        loss_hist = list(state.loss_history)
        gn_hist = list(state.grad_norm_history)
        best_loss = float(state.best_loss)
        best = snapshot_state(np.asarray(state.best), dtype=None)
        it = int(state.iteration)
    converged = False
    paused = False
    while it < iterations:
        if pause is not None and pause(it):
            paused = True
            break
        it += 1
        loss, g = value_and_grad(params)
        loss = float(loss)
        gn = float(jnp.sqrt(jnp.sum(g * g)))
        loss_hist.append(loss)
        gn_hist.append(gn)
        if registry is not None:
            registry.series("inverse_loss", it, loss, **labels)
            registry.series("inverse_grad_norm", it, gn, **labels)
            registry.counter("inverse_iterations_total")
        if progress is not None:
            progress(it, loss, gn)
        if loss < best_loss:
            best_loss = loss
            best = snapshot_state(params, dtype=None)
        if tol is not None and loss <= tol:
            converged = True
            break
        m = beta1 * m + (1.0 - beta1) * g
        v = beta2 * v + (1.0 - beta2) * g * g
        mhat = m / (1.0 - beta1 ** it)
        vhat = v / (1.0 - beta2 ** it)
        params = params - lr * mhat / (jnp.sqrt(vhat) + eps)
        if project is not None:
            params = project(params)
    out_state = None
    if paused:
        out_state = AdamState(
            iteration=it,
            params=snapshot_state(params, dtype=None),
            m=snapshot_state(m, dtype=None),
            v=snapshot_state(v, dtype=None),
            best=snapshot_state(np.asarray(best), dtype=None),
            best_loss=best_loss,
            loss_history=list(loss_hist),
            grad_norm_history=list(gn_hist))
    return InverseSolution(
        params=best, final_loss=best_loss, iterations=it,
        converged=converged, grad_norm=gn_hist[-1] if gn_hist else 0.0,
        loss_history=loss_hist, grad_norm_history=gn_hist,
        paused=paused, state=out_state)


@dataclasses.dataclass
class InverseProblem:
    """One inverse problem over final-state observations.

    ``obs_mask`` (bool (nx, ny)) marks observed cells; ``obs_values``
    holds the observed final-state values (only masked entries are
    read). For ``target="init"`` the constant coefficients (cx, cy) are
    known and ``u0`` is recovered; for ``target="diffusivity"`` the
    initial condition is known (``u0``, defaulting to the reference
    ``inidat``) and the isotropic per-cell ``kappa`` is recovered.
    """
    nx: int
    ny: int
    steps: int
    target: str
    obs_mask: np.ndarray
    obs_values: np.ndarray
    cx: float = 0.1
    cy: float = 0.1
    u0: Optional[np.ndarray] = None     # known init (diffusivity target)
    reg: float = 0.0                    # Tikhonov weight on the params
    adjoint: str = "checkpoint"
    segment: Optional[int] = None
    method: str = "auto"

    def __post_init__(self):
        if self.target not in TARGETS:
            raise ValueError(
                f"target must be one of {TARGETS}, got {self.target!r}")
        if tuple(np.shape(self.obs_mask)) != (self.nx, self.ny) or \
                tuple(np.shape(self.obs_values)) != (self.nx, self.ny):
            raise ValueError(
                f"obs_mask/obs_values must be ({self.nx}, {self.ny})")
        if not bool(np.any(self.obs_mask)):
            raise ValueError("obs_mask selects no cells")

    # -- pieces the optimizer consumes --------------------------------- #

    def known_u0(self):
        from heat2d_tpu.ops.init import inidat
        if self.u0 is not None:
            return np.asarray(self.u0, np.float32)
        return np.asarray(inidat(self.nx, self.ny))

    def initial_params(self) -> np.ndarray:
        """The optimizer's starting iterate: scattered observations for
        the init target (right where the data is), a flat mid-box field
        for diffusivity."""
        if self.target == "init":
            p = np.zeros((self.nx, self.ny), np.float32)
            p[self.obs_mask] = np.asarray(self.obs_values,
                                          np.float32)[self.obs_mask]
            return p
        return np.full((self.nx, self.ny), 0.1, np.float32)

    def project(self) -> Optional[Callable]:
        if self.target != "diffusivity":
            return None
        from heat2d_tpu.ops.stability import project_stable
        return project_stable

    def value_and_grad(self) -> Callable:
        """``params -> (loss, grad)``: the memoized compiled runner for
        this problem's COMPILE signature, with the observation data,
        known coefficients/init, and regularization weight bound as
        traced OPERANDS. Two problems sharing (grid, steps, target,
        adjoint, segment, method, reg-on/off) share ONE executable —
        the property the serving layer's signature bucketing relies on
        (a fresh closure per problem would recompile the whole
        solve+adjoint per request)."""
        import jax.numpy as jnp

        runner = loss_grad_runner(self.nx, self.ny, self.steps,
                                  self.target, self.adjoint,
                                  self.segment, self.method,
                                  bool(self.reg))
        mask = jnp.asarray(np.asarray(self.obs_mask, np.float32))
        obs = jnp.asarray(np.asarray(self.obs_values, np.float32))
        n_obs = jnp.asarray(float(np.count_nonzero(self.obs_mask)),
                            jnp.float32)
        reg = jnp.asarray(float(self.reg), jnp.float32)
        if self.target == "init":
            aux = (jnp.asarray(float(self.cx), jnp.float32),
                   jnp.asarray(float(self.cy), jnp.float32))
        else:
            aux = (jnp.asarray(self.known_u0()),)
        return functools.partial(runner, aux=aux, mask=mask, obs=obs,
                                 n_obs=n_obs, reg=reg)

    def solve(self, *, iterations: int = 100, lr: float = 0.05,
              tol: Optional[float] = None, registry=None,
              series_labels: Optional[dict] = None,
              progress: Optional[Callable] = None,
              state: Optional[AdamState] = None,
              pause: Optional[Callable[[int], bool]] = None
              ) -> InverseSolution:
        return adam_minimize(
            self.value_and_grad(), self.initial_params(),
            iterations=iterations, lr=lr, tol=tol,
            project=self.project(), registry=registry,
            series_labels=series_labels, progress=progress,
            state=state, pause=pause)
