"""The host-mediated DCN halo route: row slabs + T-deep halos over
the coordination-service KV store, bitwise-equal to the single-process
program.

Why this route exists: the global-mesh route (dist/mesh.py) needs
cross-process XLA collectives, which some harnesses — including this
repo's CI CPU backend — cannot run ("Multiprocess computations aren't
implemented on the CPU backend", the exact line the multihost tests
skip with). Rendezvous, KV, and barriers DO work there, so this
module carries the correctness anchor with REAL processes: each
process owns a contiguous row slab, extends it with a T-deep halo of
its neighbors' OWNED rows, runs ``t <= T`` plain ``stencil_step``
steps on the extended array, and re-exchanges. Held (clamped) rows at
a fake slab edge contaminate at one row per step, so after ``t``
steps every owned row — at distance >= T from any fake edge — is
BITWISE what the single-process program computes (the same
elementwise f32 arithmetic on a sliced array; no reductions, no
reassociation). The same overlap-halo argument as the fused ICI
route (PR 7), executed over DCN with the host as the DMA engine.

Strips travel as raw f32 bytes under unique per-step keys (the KV
store forbids overwrite); the consumer deletes what it read, so the
store stays bounded. A neighbor that never publishes is a
``HostLostError`` naming that host — detection, not diagnosis;
recovery is dist/topology.py's job.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Tuple

import jax
import numpy as np

from heat2d_tpu.dist.runtime import (
    KV_NS, DistWorld, kv_client, kv_get_bytes)


def slab_split(nx: int, processes: int) -> List[Tuple[int, int]]:
    """Row ranges [lo, hi) per process: near-even, order-preserving,
    exactly partitioning — the reference's MPI row decomposition
    (mpi_heat2Dn.c distributes rows the same way)."""
    if processes < 1:
        raise ValueError(f"processes must be >= 1, got {processes}")
    if nx < processes:
        raise ValueError(
            f"cannot split {nx} rows over {processes} processes")
    return [(i * nx // processes, (i + 1) * nx // processes)
            for i in range(processes)]


@functools.partial(jax.jit, static_argnames=("t",))
def _segment_steps(u, t: int, cx, cy):
    """``t`` golden stencil steps (ops/stencil.py) on an extended
    slab — the ONE compiled program both the distributed slabs and
    the single-process reference run, so parity is a statement about
    slicing, not about two compilers agreeing."""
    from jax import lax

    from heat2d_tpu.ops import stencil_step

    return lax.fori_loop(
        0, t, lambda i, v: stencil_step(v, cx, cy), u)


class DcnHaloExchanger:
    """Publishes this process's boundary strips and fetches its
    neighbors' — one exchange per segment, keyed by step so keys are
    write-once. Counts ``dist_halo_bytes_total`` (bytes moved, both
    directions) per exchange."""

    def __init__(self, world: DistWorld, depth: int, client=None, *,
                 timeout_s: float = 60.0, registry=None):
        if depth < 1:
            raise ValueError(f"halo depth must be >= 1, got {depth}")
        self.world = world
        self.depth = depth
        self._client = client
        self.timeout_s = timeout_s
        self.registry = registry

    def _kv(self):
        if self._client is None:
            self._client = kv_client()
        return self._client

    def _key(self, tag: str, src: int, dst: int) -> str:
        return f"{KV_NS}halo/{tag}/{src}-{dst}"

    def exchange(self, tag: str, top: np.ndarray,
                 bottom: np.ndarray) -> Tuple[Optional[np.ndarray],
                                              Optional[np.ndarray]]:
        """Send my top/bottom OWNED strips to my row neighbors; return
        (rows_above, rows_below) — None at a true global boundary.
        ``top``/``bottom`` are (depth, ny) f32 arrays."""
        client = self._kv()
        me = self.world.process_index
        count = self.world.process_count
        up = me - 1 if me > 0 else None
        down = me + 1 if me < count - 1 else None
        moved = 0
        # publish before fetching: both neighbors can then progress
        # regardless of arrival order
        if up is not None:
            client.key_value_set_bytes(
                self._key(tag, me, up), np.ascontiguousarray(top)
                .tobytes())
            moved += top.nbytes
        if down is not None:
            client.key_value_set_bytes(
                self._key(tag, me, down), np.ascontiguousarray(bottom)
                .tobytes())
            moved += bottom.nbytes

        def fetch(src: int, like: np.ndarray) -> np.ndarray:
            key = self._key(tag, src, me)
            buf = kv_get_bytes(client, key, self.timeout_s,
                               lost_host=src, phase=f"halo:{tag}")
            client.key_value_delete(key)   # consumed: bound the store
            return np.frombuffer(buf, dtype=np.float32).reshape(
                like.shape)

        above = fetch(up, top) if up is not None else None
        below = fetch(down, bottom) if down is not None else None
        moved += sum(a.nbytes for a in (above, below) if a is not None)
        if self.registry is not None:
            self.registry.counter("dist_halo_bytes_total", float(moved))
        return above, below


def run_process_slab(nx: int, ny: int, steps: int, *,
                     cx: float = 0.1, cy: float = 0.1,
                     depth: int = 4,
                     process_index: int = 0, process_count: int = 1,
                     exchanger: Optional[DcnHaloExchanger] = None,
                     u0: Optional[np.ndarray] = None,
                     start_step: int = 0,
                     on_segment: Optional[Callable] = None
                     ) -> Tuple[np.ndarray, int]:
    """Run this process's slab from ``start_step`` to ``steps``;
    returns (owned rows as f32 numpy, final step).

    ``u0`` is the FULL grid at ``start_step`` (default: the golden
    initial condition) — every process slices its own extension from
    it, so a resume at any step count resharding to any process count
    is just "load the checkpoint, call this" (the N-save → M-restore
    contract tests/test_dist_reshard.py pins bitwise).
    ``on_segment(step, owned)`` fires after every segment — the
    checkpoint hook."""
    import jax.numpy as jnp

    from heat2d_tpu.ops import inidat

    if process_count > 1 and exchanger is None:
        raise ValueError("multi-process slabs need an exchanger")
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} outside world of "
            f"{process_count}")
    lo, hi = slab_split(nx, process_count)[process_index]
    if process_count > 1 and min(
            h - l for l, h in slab_split(nx, process_count)) < depth:
        raise ValueError(
            f"slab of {nx} rows over {process_count} processes is "
            f"shallower than the depth-{depth} halo — a neighbor's "
            "halo would have to span TWO hosts")
    full = jnp.asarray(inidat(nx, ny) if u0 is None else u0,
                       dtype=jnp.float32)
    if full.shape != (nx, ny):
        raise ValueError(
            f"u0 shape {full.shape} does not match grid ({nx}, {ny})")
    elo = max(0, lo - depth)
    ehi = min(nx, hi + depth)
    u_ext = full[elo:ehi]
    step = start_step
    while step < steps:
        t = min(depth, steps - step)
        if process_count > 1:
            owned = np.asarray(u_ext[lo - elo:hi - elo])
            above, below = exchanger.exchange(
                f"s{step}", owned[:depth], owned[-depth:])
            parts = [p for p in (above, owned, below) if p is not None]
            u_ext = jnp.asarray(np.concatenate(parts, axis=0))
        u_ext = _segment_steps(u_ext, t, cx, cy)
        step += t
        if on_segment is not None:
            on_segment(step, np.asarray(u_ext[lo - elo:hi - elo]))
    return np.asarray(u_ext[lo - elo:hi - elo]), step
