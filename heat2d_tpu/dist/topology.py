"""Failure-domain unification: one host loss, one coordinated move.

Before this module the platform had two disjoint failure brains: the
fleet supervisor answers PROCESS death (restart the worker), the mesh
health monitor answers DEVICE failure (quarantine, shrink, requeue).
A lost HOST is both at once — its process dies AND every device it
owned vanishes from the global mesh — and handling the two halves
independently races: a mesh launch could pick the dead host's devices
after the supervisor already declared the process gone.

``FailureDomainBridge.on_host_lost`` makes it ONE transaction under
the existing seq-fence discipline (mesh/health.py, mesh/degrade.py):

1. capture the monitor's event ordinal,
2. quarantine every device of the lost host (reason ``host_lost`` —
   now part of the documented ``mesh_quarantine_total{reason}``
   vocabulary),
3. run the worker-failover action (resume from the last committed
   checkpoint on the shrunken world) while the fence already covers
   the quarantines,
4. append the transaction row.

Any launch fenced AFTER the transaction sees only survivor devices,
so the unchanged ``serving_invariant`` proves the combined move the
same way it proves single-host quarantines — the acceptance check
the host-kill soak (dist/cli.py --soak --kill-host) runs end to end.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Tuple

from heat2d_tpu.dist.runtime import DistWorld


class PodTopology:
    """host -> global device ordinals. Built from a live ``DistWorld``
    (hosts are processes) or from an injected map for simulation — the
    bridge and its tests never care which."""

    def __init__(self, device_host: Dict[int, int]):
        self.device_host = dict(device_host)
        if not self.device_host:
            raise ValueError("topology needs at least one device")

    @classmethod
    def from_world(cls, world: DistWorld) -> "PodTopology":
        return cls({g: p for g, p in enumerate(world.device_process)})

    @property
    def n_devices(self) -> int:
        return len(self.device_host)

    @property
    def hosts(self) -> Tuple[int, ...]:
        return tuple(sorted(set(self.device_host.values())))

    def devices_of(self, host: int) -> Tuple[int, ...]:
        return tuple(sorted(g for g, h in self.device_host.items()
                            if h == host))

    def host_of(self, device: int) -> int:
        return self.device_host[device]


def pod_monitor(n_devices: int, *, registry=None,
                clock: Callable[[], float] = time.monotonic):
    """A ``HealthMonitor`` whose device space is the POD's ordinals.

    The stock constructor sizes itself from the locally attached
    device list — correct for the single-host mesh engines, wrong for
    a bridge convicting devices on OTHER hosts when the backend does
    not enumerate them globally. The monitor itself is index-based
    throughout (quarantine/survivors/seq never touch a jax device),
    so widening the count is safe; only ``probe()`` would — and the
    bridge never probes a dead host."""
    from heat2d_tpu.mesh.health import HealthMonitor

    m = HealthMonitor(registry=registry, clock=clock)
    m.n_devices = int(n_devices)
    return m


class FailureDomainBridge:
    """The one place a host loss turns into mesh state (module
    docstring). ``monitor`` is the existing ``mesh.health
    .HealthMonitor`` — it must span the POD's devices, not one
    host's, or the bridge would convict devices it cannot name."""

    def __init__(self, topology: PodTopology, monitor, *,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic):
        if monitor.n_devices < topology.n_devices:
            raise ValueError(
                f"monitor spans {monitor.n_devices} devices but the "
                f"pod has {topology.n_devices} — quarantines would "
                "fall outside the book")
        self.topology = topology
        self.monitor = monitor
        self.registry = registry
        self.clock = clock
        #: every coordinated shrink+failover, in order — the run
        #: record's ``transactions`` block
        self.transactions: list = []

    def on_host_lost(self, host: int, *,
                     failover: Optional[Callable[[], dict]] = None
                     ) -> dict:
        """The coordinated move: quarantine the host's devices, run
        the failover action, return the transaction row. Idempotent
        per device (re-reporting a lost host re-quarantines nothing);
        the failover still runs — a second report may carry a fresher
        checkpoint to resume from."""
        t0 = self.clock()
        seq_before = self.monitor.seq()
        devices = self.topology.devices_of(host)
        convicted = [d for d in devices
                     if self.monitor.quarantine(d, "host_lost")]
        # the fence every post-loss launch must carry: it covers the
        # quarantines above, so serving_invariant can prove no launch
        # fenced here-or-later ever touched the dead host's devices
        fence = self.monitor.seq()
        result = failover() if failover is not None else None
        row = {
            "host": int(host),
            "devices": list(devices),
            "quarantined": convicted,
            "seq_before": seq_before,
            "health_seq": fence,
            "survivors": list(self.monitor.survivors()),
            "failover": result,
            "recovery_s": self.clock() - t0,
        }
        self.transactions.append(row)
        if self.registry is not None:
            self.registry.counter("dist_host_lost_total")
            self.registry.observe("dist_host_recovery_s",
                                  row["recovery_s"])
        return row

    def snapshot(self) -> dict:
        """Run-record block: topology + monitor + transactions."""
        return {
            "hosts": list(self.topology.hosts),
            "n_devices": self.topology.n_devices,
            "monitor": self.monitor.snapshot(),
            "transactions": [dict(t) for t in self.transactions],
        }
