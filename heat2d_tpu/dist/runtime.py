"""Multihost bring-up: rendezvous, topology, bounded liveness.

``parallel/multihost.py`` (the MPI_Init analogue) grew into this
module: it still owns the raw ``jax.distributed.initialize`` call and
stays importable unchanged, while everything a *pod* needs on top
lives here —

- ``bring_up`` — rendezvous + a ``DistWorld``: the process topology,
  local/global device maps, and DCN-vs-ICI link classification per
  device pair that every other dist layer consults.
- ``KVBarrier`` — a BOUNDED barrier over the coordination-service KV
  store: a peer that never arrives is a ``HostLostError`` naming the
  missing process(es), not an eternal hang. Clock and sleep are
  injectable, so the timeout arithmetic is deterministically
  testable against a fake client.
- ``Heartbeat`` — seq-keyed liveness beacons per process; age is
  measured by the LOCAL clock since a peer's counter last advanced
  (no cross-host clock comparison — the reference's MPI world never
  had synchronized clocks either, SURVEY.md §2.4).

KV discipline (probed semantics of this jaxlib's coordination
service): ``key_value_set`` on an existing key raises ALREADY_EXISTS
— so every writer here uses UNIQUE sequence-numbered keys and
explicit ``key_value_delete`` GC; ``blocking_key_value_get`` raises
DEADLINE_EXCEEDED on timeout — mapped to ``HostLostError`` at every
call site via ``kv_get_bytes``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

from heat2d_tpu.parallel.multihost import (   # noqa: F401  (grown API)
    gather_to_host, initialize_distributed, shutdown_distributed,
    world_summary)

#: every coordination-service key this repo writes lives under one
#: namespace, so a ``key_value_dir_get`` sweep can enumerate (and a
#: delete can GC) without touching jax-internal keys
KV_NS = "heat2d/"

#: link classes ``DistWorld.link_kind`` hands out — the vocabulary the
#: tune link model (tune/measure.py) and the scheduler's seam pricing
#: (mesh/scheduler.py) price against (docs/DISTRIBUTED.md link table)
LINK_KINDS = ("local", "ici", "dcn")


class HostLostError(RuntimeError):
    """A peer process (= host) failed to show up inside a bounded
    wait: missed a barrier, stopped heartbeating, or never published
    its halo/checkpoint shard. Carries WHICH hosts and during WHAT
    phase, so recovery can quarantine the right failure domain
    instead of guessing from a timeout."""

    def __init__(self, hosts, phase: str, detail: str = ""):
        self.hosts = tuple(sorted(int(h) for h in hosts))
        self.phase = phase
        msg = (f"host(s) {list(self.hosts)} lost during {phase}"
               + (f": {detail}" if detail else ""))
        super().__init__(msg)


def elect_recovery_owner(survivors) -> int:
    """The deterministic post-loss election: the LOWEST surviving
    process index owns recovery (assembles state, relaunches, writes
    the record) — every survivor computes the same answer from the
    same ``HostLostError``, no extra round trip."""
    survivors = sorted(int(s) for s in survivors)
    if not survivors:
        raise ValueError("no survivors to elect from")
    return survivors[0]


def kv_client():
    """The coordination-service KV client (the jax.distributed
    rendezvous already owns one; this just reaches it). Raises
    RuntimeError when the process never rendezvoused — callers in
    single-process worlds must not get here."""
    from jax._src import distributed

    client = getattr(distributed.global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "no coordination-service client: jax.distributed was "
            "never initialized in this process (single-process "
            "world, or bring_up() not called)")
    return client


def _is_deadline(exc: BaseException) -> bool:
    """The timeout verdicts both the real coordination service
    (XlaRuntimeError DEADLINE_EXCEEDED) and test fakes
    (TimeoutError) hand back."""
    return (isinstance(exc, TimeoutError)
            or "DEADLINE_EXCEEDED" in str(exc))


def _is_severed(exc: BaseException) -> bool:
    """The coordination service itself became unreachable — the
    COORDINATOR host (process 0 runs the service in-process) is the
    casualty, whatever key we were waiting on."""
    s = str(exc)
    return any(tag in s for tag in
               ("UNAVAILABLE", "failed to connect", "Connection res",
                "DISCONNECTED", "CANCELLED"))


def kv_get_bytes(client, key: str, timeout_s: float, *,
                 lost_host: int, phase: str) -> bytes:
    """Blocking KV get with the one loss-mapping every dist layer
    shares: a deadline is a ``HostLostError`` naming the host that
    was supposed to publish ``key``; a severed service names the
    coordinator (host 0)."""
    try:
        return client.blocking_key_value_get_bytes(
            key, int(timeout_s * 1000))
    except Exception as e:                   # noqa: BLE001 — re-raised
        if _is_deadline(e):
            raise HostLostError(
                (lost_host,), phase,
                f"no value at {key!r} within {timeout_s}s") from e
        if _is_severed(e):
            raise HostLostError(
                (0,), phase,
                f"coordination service unreachable waiting on "
                f"{key!r}") from e
        raise


@dataclass(frozen=True)
class DistWorld:
    """The pod topology every dist layer consults: who am I, who else
    exists, which devices live where, and what class of link joins
    any device pair.

    ``device_process[g]`` is the owning process of global device
    ordinal ``g``; ``device_slice`` (optional) is the ICI domain per
    device — on TPU pods devices on DIFFERENT hosts within one slice
    still talk ICI, so slice identity (not process identity) decides
    ici-vs-dcn when the platform exposes it. Constructable directly
    with injected maps for simulation tests; ``from_env`` reads the
    live jax state."""

    process_index: int
    process_count: int
    coordinator: Optional[str] = None
    device_process: Tuple[int, ...] = field(default_factory=tuple)
    device_slice: Optional[Tuple[int, ...]] = None

    @classmethod
    def from_env(cls, coordinator: Optional[str] = None) -> "DistWorld":
        import jax

        devs = jax.devices()
        slices = tuple(getattr(d, "slice_index", None) for d in devs)
        # slice identity only means ICI on accelerators; CPU devices
        # report slice_index 0 too, but cross-process CPU transport
        # is socket (DCN-class) — fall back to process identity there
        use_slices = (bool(devs)
                      and all(s is not None for s in slices)
                      and not all(getattr(d, "platform", "") == "cpu"
                                  for d in devs))
        return cls(
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            coordinator=coordinator,
            device_process=tuple(d.process_index for d in devs),
            device_slice=slices if use_slices else None)

    # -- identity ------------------------------------------------------ #

    @property
    def is_coordinator(self) -> bool:
        """Process 0 hosts the coordination service (jax.distributed
        runs it inside the process at the coordinator address)."""
        return self.process_index == 0

    @property
    def n_devices(self) -> int:
        return len(self.device_process)

    def devices_of(self, process: int) -> Tuple[int, ...]:
        """Global device ordinals owned by ``process`` — the failure
        domain a host loss takes out in one piece."""
        return tuple(g for g, p in enumerate(self.device_process)
                     if p == process)

    def local_devices(self) -> Tuple[int, ...]:
        return self.devices_of(self.process_index)

    def peers(self) -> Tuple[int, ...]:
        return tuple(p for p in range(self.process_count)
                     if p != self.process_index)

    # -- links --------------------------------------------------------- #

    def link_kind(self, a: int, b: int) -> str:
        """'local' (same device), 'ici' (same ICI domain: same slice
        when the platform says, same process otherwise), 'dcn'
        (everything across). The asymmetry the tune link model and
        the scheduler's seam pricing consume."""
        if a == b:
            return "local"
        if self.device_slice is not None:
            return ("ici" if self.device_slice[a] == self.device_slice[b]
                    else "dcn")
        return ("ici" if self.device_process[a] == self.device_process[b]
                else "dcn")

    def link_census(self) -> dict:
        """Unordered device-pair counts per link class — the run
        record's one-glance topology shape."""
        out = {k: 0 for k in LINK_KINDS if k != "local"}
        n = self.n_devices
        for a in range(n):
            for b in range(a + 1, n):
                out[self.link_kind(a, b)] += 1
        return out

    def summary(self) -> dict:
        return {
            "process_index": self.process_index,
            "process_count": self.process_count,
            "coordinator": self.coordinator,
            "n_devices": self.n_devices,
            "device_process": list(self.device_process),
            "links": self.link_census(),
        }


def bring_up(coordinator: Optional[str] = None,
             num_processes: Optional[int] = None,
             process_id: Optional[int] = None, *,
             registry=None,
             clock: Callable[[], float] = time.monotonic) -> DistWorld:
    """Rendezvous (when a multi-process launch line asks for one) and
    return the ``DistWorld``. Single-process degrades to a 1-process
    world without touching jax.distributed — the same code path runs
    under mpiexec-style launches and plain CLI invocations.

    Records ``dist_rendezvous_s`` (wall time from call to connected
    world) when a registry rides along."""
    t0 = clock()
    multi = (num_processes or 1) > 1 or coordinator is not None
    if multi:
        initialize_distributed(coordinator, num_processes, process_id)
    world = DistWorld.from_env(coordinator)
    if registry is not None:
        registry.gauge("dist_rendezvous_s", clock() - t0)
    return world


class KVBarrier:
    """A named, BOUNDED barrier over the KV store.

    Each ``wait(name)`` call publishes a unique per-invocation key
    (``heat2d/bar/<name>/<n>/<pid>`` — the per-process invocation
    counter ``n`` must agree across processes, the same call-ordering
    contract MPI barriers carry) and polls the directory until all
    ``process_count`` peers appear or the deadline passes — at which
    point the MISSING peers are named in a ``HostLostError``. Keys
    from two invocations back are GC'd (a straggler may still be
    reading the previous round's).

    Why not the service's native ``wait_at_barrier``: its timeout
    verdict says only "deadline exceeded", not WHO was missing — this
    barrier exists precisely to name the corpse."""

    def __init__(self, world: DistWorld, client=None, *,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 poll: float = 0.02, registry=None):
        self.world = world
        self._client = client
        self.clock = clock
        self.sleep = sleep
        self.poll = poll
        self.registry = registry
        self._counts: dict = {}

    def _kv(self):
        if self._client is None:
            self._client = kv_client()
        return self._client

    def wait(self, name: str, timeout_s: float = 60.0) -> float:
        """Block until every process arrives; returns seconds waited.
        Single-process worlds return immediately."""
        if self.world.process_count <= 1:
            return 0.0
        n = self._counts[name] = self._counts.get(name, -1) + 1
        client = self._kv()
        prefix = f"{KV_NS}bar/{name}/{n}/"
        t0 = self.clock()
        client.key_value_set(prefix + str(self.world.process_index), "1")
        want = set(range(self.world.process_count))
        while True:
            rows = client.key_value_dir_get(prefix)
            seen = {int(k.rsplit("/", 1)[-1]) for k, _ in rows}
            if seen >= want:
                break
            if self.clock() - t0 >= timeout_s:
                raise HostLostError(
                    sorted(want - seen), f"barrier:{name}",
                    f"{len(seen)}/{len(want)} arrived in {timeout_s}s")
            self.sleep(self.poll)
        waited = self.clock() - t0
        if self.registry is not None:
            self.registry.observe("dist_barrier_wait_s", waited,
                                  barrier=name)
        if n >= 2:
            # GC the round a straggler can no longer be reading
            client.key_value_delete(f"{KV_NS}bar/{name}/{n - 2}/")
        return waited


class Heartbeat:
    """Per-process liveness beacons with local-clock aging.

    ``beat()`` publishes the next sequence-numbered key under
    ``heat2d/hb/<pid>/`` and GCs two behind; ``start()`` runs beats on
    a daemon thread every ``interval_s``. ``ages()`` reads every
    peer's directory and reports seconds since that peer's counter
    LAST ADVANCED — measured entirely by this process's clock, so no
    cross-host clock agreement is assumed. ``require_live`` turns a
    stale peer into a named ``HostLostError``.

    Clock is injectable (and ``beat``/``ages`` are callable without
    the thread) so staleness arithmetic is deterministic in tests."""

    def __init__(self, world: DistWorld, client=None, *,
                 interval_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        self.world = world
        self._client = client
        self.interval_s = interval_s
        self.clock = clock
        self.registry = registry
        self._n = 0
        self._last: dict = {}   # peer -> (last counter, local time)
        self._t0 = clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _kv(self):
        if self._client is None:
            self._client = kv_client()
        return self._client

    # -- writer -------------------------------------------------------- #

    def beat(self) -> int:
        """Publish one beacon; returns its sequence number."""
        client = self._kv()
        self._n += 1
        pid = self.world.process_index
        client.key_value_set(f"{KV_NS}hb/{pid}/{self._n}", "1")
        if self._n >= 3:
            client.key_value_delete(f"{KV_NS}hb/{pid}/{self._n - 2}")
        return self._n

    def start(self) -> None:
        if self.world.process_count <= 1 or self._thread is not None:
            return
        self.beat()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.beat()
                except Exception:      # noqa: BLE001 — beacon only;
                    return             # a dead service ends the loop

        self._thread = threading.Thread(
            target=loop, name="heat2d-dist-heartbeat", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s)
            self._thread = None

    # -- monitor ------------------------------------------------------- #

    def ages(self) -> dict:
        """{peer process -> seconds since its counter last advanced}.
        A peer that never published ages from this monitor's birth."""
        if self.world.process_count <= 1:
            return {}
        client = self._kv()
        now = self.clock()
        out = {}
        for peer in self.world.peers():
            rows = client.key_value_dir_get(f"{KV_NS}hb/{peer}/")
            cur = max((int(k.rsplit("/", 1)[-1]) for k, _ in rows),
                      default=0)
            last_n, last_t = self._last.get(peer, (0, self._t0))
            if cur > last_n:
                last_n, last_t = cur, now
                self._last[peer] = (last_n, last_t)
            age = now - last_t
            out[peer] = age
            if self.registry is not None:
                self.registry.gauge("dist_heartbeat_age_s", age,
                                    process=str(peer))
        return out

    def stale(self, max_age_s: float) -> Tuple[int, ...]:
        return tuple(sorted(p for p, age in self.ages().items()
                            if age > max_age_s))

    def require_live(self, max_age_s: float,
                     phase: str = "heartbeat") -> None:
        dead = self.stale(max_age_s)
        if dead:
            raise HostLostError(
                dead, phase,
                f"no beacon advance within {max_age_s}s")
