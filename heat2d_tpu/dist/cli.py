"""``heat2d-tpu-dist`` — the mpiexec-style multihost launch surface.

Three shapes, one binary (the reference's ``mpiexec -np N ./heat``
launch line, SURVEY.md §2.4, with the driver legs CI's dist-gate
runs bolted on):

- **worker** (``--process-id`` given, or ``--num-processes 1``): one
  process of the pod. Rendezvous, heartbeats, the DCN slab route
  (dist/exchange.py), collective KV-gathered checkpoints, and — on a
  ``HostLostError`` — the unified shrink+failover transaction
  (dist/topology.py) finishing the job from the last committed
  checkpoint, all under the seq-fenced ``serving_invariant``.
- **``--selftest``**: spawns its own 2-process world, then asserts
  the final grid is BITWISE identical to the single-process program
  on the same grid — the correctness anchor.
- **``--soak --kill-host``**: spawns a paced 2-process soak, SIGKILLs
  the non-coordinator host after the first committed checkpoint, and
  asserts the survivor recovered through the coordinated
  shrink+failover path: bitwise final parity AND
  ``serving_invariant.ok`` in the kind="dist" run record.

Post-loss exits use ``os._exit``: jax's atexit shutdown would block
waiting for the dead peer to disconnect — a survivor that already
wrote and fsynced its outputs owes the corpse nothing.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time

import numpy as np

from heat2d_tpu.dist.exchange import (
    DcnHaloExchanger, run_process_slab, slab_split)
from heat2d_tpu.dist.runtime import (
    KV_NS, Heartbeat, HostLostError, KVBarrier, bring_up,
    elect_recovery_owner, kv_client, kv_get_bytes)
from heat2d_tpu.dist.topology import (
    FailureDomainBridge, PodTopology, pod_monitor)


def _args(argv=None):
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-dist",
        description="multihost pod runtime (docs/DISTRIBUTED.md)")
    w = p.add_argument_group("world (mpiexec-style)")
    w.add_argument("--coordinator", default=None,
                   help="host:port of process 0's coordination service")
    w.add_argument("--num-processes", type=int, default=1)
    w.add_argument("--process-id", type=int, default=None)
    g = p.add_argument_group("problem")
    g.add_argument("--nx", type=int, default=48)
    g.add_argument("--ny", type=int, default=32)
    g.add_argument("--steps", type=int, default=16)
    g.add_argument("--segment", type=int, default=4,
                   help="halo depth = steps per exchange segment")
    g.add_argument("--cx", type=float, default=0.1)
    g.add_argument("--cy", type=float, default=0.1)
    s = p.add_argument_group("state")
    s.add_argument("--checkpoint", default=None,
                   help="collective checkpoint path (KV-gathered, "
                        "committed crash-consistently by process 0)")
    s.add_argument("--checkpoint-every", type=int, default=0,
                   help="steps between checkpoints (0 = off)")
    s.add_argument("--resume", default=None,
                   help="checkpoint to resume from (any saving "
                        "process count — reshard is a slice)")
    s.add_argument("--out", default=None,
                   help="final full-grid raw f32 (written by the "
                        "recovery owner / process 0)")
    s.add_argument("--run-record", default=None)
    t = p.add_argument_group("liveness")
    t.add_argument("--halo-timeout", type=float, default=60.0,
                   help="bounded wait for a peer's strip/shard "
                        "before declaring it lost")
    t.add_argument("--heartbeat", type=float, default=0.0,
                   help="beacon interval seconds (0 = off)")
    t.add_argument("--pace", type=float, default=0.0,
                   help="sleep per segment (soak windowing)")
    t.add_argument("--marker", default=None,
                   help="file process 0 touches after the first "
                        "committed checkpoint (soak kill window)")
    d = p.add_argument_group("driver legs (spawn their own world)")
    d.add_argument("--selftest", action="store_true",
                   help="2-process vs single-process bitwise parity")
    d.add_argument("--soak", action="store_true")
    d.add_argument("--kill-host", action="store_true",
                   help="SIGKILL the non-coordinator host mid-soak")
    d.add_argument("--outdir", default=None)
    return p.parse_args(argv)


def _say(world, msg: str) -> None:
    print(f"[dist p{world.process_index}/{world.process_count}] {msg}",
          flush=True)


def _metric_totals(reg) -> dict:
    """The dist_* families as plain numbers for the run record."""
    out = {}
    for name in ("dist_halo_bytes_total", "dist_host_lost_total",
                 "dist_checkpoint_gather_bytes_total"):
        vals = reg.find_counters(name)
        if vals:
            out[name] = float(sum(vals.values()))
    for name in ("dist_rendezvous_s", "dist_heartbeat_age_s"):
        vals = reg.find_gauges(name)
        if vals:
            out[name] = {("" if not k else str(dict(k))): v
                         for k, v in vals.items()}
    return out


def _write_record(path, extra: dict) -> None:
    from heat2d_tpu.io.binary import write_text_atomic
    from heat2d_tpu.obs.record import build_record

    rec = build_record("dist", extra=extra)
    write_text_atomic(json.dumps(rec, indent=2, default=str,
                                 sort_keys=True), path)


# ------------------------------------------------------------------ #
# worker
# ------------------------------------------------------------------ #

def _load_state(args):
    """(full grid at start, start step) — resume is process-count
    agnostic: every process loads the FULL committed grid and slices
    its own slab (the N-save → M-restore reshard contract)."""
    from heat2d_tpu.io import load_checkpoint
    from heat2d_tpu.ops import inidat

    if args.resume:
        grid, step, _ = load_checkpoint(args.resume)
        return np.asarray(grid, np.float32), int(step)
    return np.asarray(inidat(args.nx, args.ny), np.float32), 0


def _save_collective(args, world, barrier, owned, step, reg) -> None:
    """N-process checkpoint: every process publishes its OWNED slab
    to the KV store; process 0 assembles the full grid and commits it
    through the crash-consistent single-file path (io/binary.py), and
    the closing barrier keeps every rank behind the commit — the same
    no-rank-outruns-the-commit rule write_binary_sharded enforces."""
    from heat2d_tpu.io import save_checkpoint

    cfg = {"nx": args.nx, "ny": args.ny, "steps": args.steps,
           "segment": args.segment, "cx": args.cx, "cy": args.cy,
           "processes": world.process_count}
    if world.process_count == 1:
        save_checkpoint(owned, step, cfg, args.checkpoint)
        return
    client = kv_client()
    client.key_value_set_bytes(
        f"{KV_NS}ck/{step}/{world.process_index}", owned.tobytes())
    reg.counter("dist_checkpoint_gather_bytes_total",
                float(owned.nbytes))
    if world.process_index == 0:
        slabs = []
        for pr, (lo, hi) in enumerate(
                slab_split(args.nx, world.process_count)):
            buf = kv_get_bytes(
                client, f"{KV_NS}ck/{step}/{pr}", args.halo_timeout,
                lost_host=pr, phase=f"checkpoint:{step}")
            slabs.append(np.frombuffer(buf, np.float32)
                         .reshape(hi - lo, args.ny))
        save_checkpoint(np.concatenate(slabs, axis=0), step, cfg,
                        args.checkpoint)
    barrier.wait(f"ck{step}", timeout_s=args.halo_timeout)
    if world.process_index == 0:
        client.key_value_delete(f"{KV_NS}ck/{step}/")


def _gather_final(args, world, owned) -> np.ndarray:
    """Process 0 assembles the final grid from every rank's owned
    slab (peers publish and exit; the KV store outlives them)."""
    if world.process_count == 1:
        return owned
    client = kv_client()
    me = world.process_index
    if me != 0:
        client.key_value_set_bytes(f"{KV_NS}final/{me}",
                                   owned.tobytes())
        return owned
    slabs = [owned]
    for pr, (lo, hi) in list(enumerate(
            slab_split(args.nx, world.process_count)))[1:]:
        buf = kv_get_bytes(
            client, f"{KV_NS}final/{pr}", args.halo_timeout,
            lost_host=pr, phase="final_gather")
        slabs.append(np.frombuffer(buf, np.float32)
                     .reshape(hi - lo, args.ny))
    return np.concatenate(slabs, axis=0)


def _worker(args) -> int:
    from heat2d_tpu.mesh.degrade import serving_invariant
    from heat2d_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    world = bring_up(args.coordinator, args.num_processes,
                     args.process_id, registry=reg)
    _say(world, f"world up: {world.summary()}")
    barrier = KVBarrier(world, registry=reg)
    hb = None
    if args.heartbeat > 0 and world.process_count > 1:
        hb = Heartbeat(world, interval_s=args.heartbeat, registry=reg)
        hb.start()

    topology = PodTopology.from_world(world)
    monitor = pod_monitor(topology.n_devices, registry=reg)
    bridge = FailureDomainBridge(topology, monitor, registry=reg)
    sig = f"dist:{args.nx}x{args.ny}:s{args.steps}"
    launch_log = [{"signature": sig,
                   "mesh": {"devices": list(range(topology.n_devices)),
                            "health_seq": monitor.seq()}}]

    u0, start = _load_state(args)
    exchanger = None
    if world.process_count > 1:
        exchanger = DcnHaloExchanger(
            world, args.segment, timeout_s=args.halo_timeout,
            registry=reg)

    state = {"last_ck": start if args.resume else None}

    def on_segment(step, owned):
        if args.pace > 0:
            time.sleep(args.pace)
        if hb is not None:
            hb.ages()     # sample dist_heartbeat_age_s each segment
        due = (args.checkpoint and args.checkpoint_every
               and step % args.checkpoint_every == 0)
        if due:
            _save_collective(args, world, barrier, owned, step, reg)
            state["last_ck"] = step
            if args.marker and world.process_index == 0 \
                    and not os.path.exists(args.marker):
                from heat2d_tpu.io.binary import write_text_atomic
                write_text_atomic(str(step), args.marker)

    try:
        barrier.wait("world-up", timeout_s=args.halo_timeout)
        owned, step = run_process_slab(
            args.nx, args.ny, args.steps, cx=args.cx, cy=args.cy,
            depth=args.segment, process_index=world.process_index,
            process_count=world.process_count, exchanger=exchanger,
            u0=u0, start_step=start, on_segment=on_segment)
        full = _gather_final(args, world, owned)
        if world.process_index == 0:
            if args.out:
                from heat2d_tpu.io import write_binary
                write_binary(full, args.out)
            if args.run_record:
                _write_record(args.run_record, {
                    "leg": "run", "world": world.summary(),
                    "steps_done": step, "resume_from_step": start,
                    "last_checkpoint_step": state["last_ck"],
                    "launch_log": launch_log,
                    "serving_invariant":
                        serving_invariant(monitor, launch_log),
                    "bridge": bridge.snapshot(),
                    "metrics": _metric_totals(reg),
                })
            _say(world, f"done: steps={step}")
        barrier.wait("done", timeout_s=args.halo_timeout)
        if hb is not None:
            hb.stop()
        return 0
    except HostLostError as e:
        return _recover(args, world, e, bridge, monitor, launch_log,
                        hb, reg, sig)


def _recover(args, world, e, bridge, monitor, launch_log, hb, reg,
             sig) -> int:
    """The unified shrink+failover transaction, run by the elected
    recovery owner; standby survivors exit clean. Never returns on
    the owner path — outputs are flushed and the process leaves via
    ``os._exit`` (module docstring)."""
    from heat2d_tpu.mesh.degrade import serving_invariant

    lost = set(e.hosts)
    survivors = [p for p in range(world.process_count)
                 if p not in lost]
    _say(world, f"HOST LOST: {e}")
    ages = {}
    if hb is not None:
        try:
            ages = hb.ages()
        except Exception:      # noqa: BLE001 — service may be gone
            pass
        hb.stop()
    owner = elect_recovery_owner(survivors)
    if world.process_index != owner:
        _say(world, f"standby survivor; p{owner} owns recovery")
        sys.stdout.flush()
        os._exit(0)

    def failover() -> dict:
        fence = monitor.seq()
        surv_devices = monitor.survivors()
        u0, ck_step = _load_state(argparse.Namespace(
            resume=(args.checkpoint
                    if args.checkpoint
                    and os.path.exists(str(args.checkpoint)
                                       + ".meta.json")
                    else None),
            nx=args.nx, ny=args.ny))
        owned, step = run_process_slab(
            args.nx, args.ny, args.steps, cx=args.cx, cy=args.cy,
            depth=args.segment, u0=u0, start_step=ck_step)
        launch_log.append({"signature": sig,
                           "mesh": {"devices": list(surv_devices),
                                    "health_seq": fence}})
        if args.out:
            from heat2d_tpu.io import write_binary
            write_binary(owned, args.out)
        return {"resume_step": ck_step, "steps_done": step,
                "survivor_devices": list(surv_devices)}

    for i, host in enumerate(sorted(lost)):
        last = i == len(lost) - 1
        txn = bridge.on_host_lost(
            host, failover=failover if last else None)
    inv = serving_invariant(monitor, launch_log)
    if args.run_record:
        _write_record(args.run_record, {
            "leg": "host_loss_recovery", "world": world.summary(),
            "lost_hosts": sorted(lost), "phase": e.phase,
            "error": str(e), "heartbeat_ages": ages,
            "transaction": txn, "launch_log": launch_log,
            "serving_invariant": inv,
            "bridge": bridge.snapshot(),
            "metrics": _metric_totals(reg),
        })
    _say(world, f"recovered through shrink+failover: {txn['failover']}"
                f" serving_invariant_ok={inv['ok']}")
    sys.stdout.flush()
    os._exit(0 if inv["ok"] else 4)


# ------------------------------------------------------------------ #
# driver legs
# ------------------------------------------------------------------ #

def _reference(args) -> np.ndarray:
    """The single-process program on the same global grid — the
    bitwise anchor both driver legs compare against."""
    ref, _ = run_process_slab(args.nx, args.ny, args.steps,
                              cx=args.cx, cy=args.cy,
                              depth=args.segment)
    return np.asarray(ref, np.float32)


def _plain_loop(args) -> np.ndarray:
    """The UN-segmented single-process program: one COMPILED
    ``stencil_step`` per step, no segment chunking — proves the
    segment fori_loop itself changes nothing. Jitted because every
    engine in this repo serves compiled programs (eager dispatch is
    not bitwise-comparable: XLA's jit pipeline contracts mul+add
    into fma on CPU, a different — not wrong — f32 rounding)."""
    import jax

    from heat2d_tpu.ops import inidat, stencil_step

    step = jax.jit(stencil_step)
    u = inidat(args.nx, args.ny)
    for _ in range(args.steps):
        u = step(u, args.cx, args.cy)
    return np.asarray(u, np.float32)


def _worker_argv(args, outdir, extra):
    def argv_fn(i, coordinator):
        return [sys.executable, "-m", "heat2d_tpu.dist.cli",
                "--coordinator", coordinator,
                "--num-processes", "2", "--process-id", str(i),
                "--nx", str(args.nx), "--ny", str(args.ny),
                "--steps", str(args.steps),
                "--segment", str(args.segment),
                "--cx", str(args.cx), "--cy", str(args.cy),
                "--out", os.path.join(outdir, "dist_final.bin"),
                "--run-record",
                os.path.join(outdir, "worker_record.json"),
                "--heartbeat", "0.5"] + extra
    return argv_fn


def _selftest(args) -> int:
    from heat2d_tpu.dist.harness import clean_env, spawn_world

    # jax initializes its backend lazily: pinning cpu before the
    # in-process reference keeps driver and (cpu-forced) children on
    # the same arithmetic
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    outdir = args.outdir or tempfile.mkdtemp(prefix="heat2d-dist-")
    os.makedirs(outdir, exist_ok=True)
    results = spawn_world(
        2, _worker_argv(args, outdir, []),
        env=clean_env({"JAX_PLATFORMS": "cpu"}), timeout=300)
    if not all(r.ok for r in results):
        for r in results:
            print(f"--- process {r.process_id} "
                  f"(rc={r.returncode}) ---\n{r.output}")
        print("DIST SELFTEST FAILED: world did not complete")
        return 1
    got = np.fromfile(os.path.join(outdir, "dist_final.bin"),
                      np.float32).reshape(args.nx, args.ny)
    ref = _reference(args)
    plain = _plain_loop(args)
    bitwise = got.tobytes() == ref.tobytes()
    bitwise_plain = got.tobytes() == plain.tobytes()
    _write_record(
        args.run_record
        or os.path.join(outdir, "selftest_record.json"),
        {"leg": "selftest",
         "config": {"nx": args.nx, "ny": args.ny,
                    "steps": args.steps,
                    "segment": args.segment},
         "bitwise_equal": bitwise,
         "bitwise_vs_plain_loop": bitwise_plain,
         "outdir": outdir})
    print(f"DIST SELFTEST nx={args.nx} ny={args.ny} "
          f"steps={args.steps} segment={args.segment} "
          f"bitwise_equal={bitwise} "
          f"bitwise_vs_plain_loop={bitwise_plain}")
    return 0 if bitwise and bitwise_plain else 1


def _soak_kill_host(args) -> int:
    import subprocess

    from heat2d_tpu.dist.harness import clean_env, free_port

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    outdir = args.outdir or tempfile.mkdtemp(prefix="heat2d-dist-")
    os.makedirs(outdir, exist_ok=True)
    ck = os.path.join(outdir, "ck.bin")
    marker = os.path.join(outdir, "marker")
    wrec = os.path.join(outdir, "worker_record.json")
    coordinator = f"localhost:{free_port()}"
    env = clean_env({"JAX_PLATFORMS": "cpu"})
    argv_fn = _worker_argv(
        args, outdir,
        ["--checkpoint", ck,
         "--checkpoint-every", str(args.checkpoint_every or 8),
         "--pace", str(args.pace or 0.4),
         "--marker", marker,
         "--halo-timeout", str(min(args.halo_timeout, 8.0))])
    procs = [subprocess.Popen(
        argv_fn(i, coordinator), env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]

    def fail(why: str) -> int:
        for q in procs:
            if q.poll() is None:
                q.kill()
        outs = [q.communicate()[0] for q in procs]
        for i, o in enumerate(outs):
            print(f"--- process {i} ---\n{o}")
        print(f"DIST SOAK FAILED: {why}")
        return 1

    deadline = time.monotonic() + 180
    while not os.path.exists(marker):
        if time.monotonic() > deadline:
            return fail("no checkpoint marker within 180s")
        if any(q.poll() is not None for q in procs):
            return fail("a worker exited before the kill window")
        time.sleep(0.02)
    victim = procs[1]                 # NON-coordinator: the service
    if victim.poll() is not None:     # lives inside process 0
        return fail("victim finished before the kill")
    os.kill(victim.pid, signal.SIGKILL)
    kill_t = time.monotonic()
    print(f"killed host 1 (pid {victim.pid}) after marker "
          f"{marker}", flush=True)
    victim.communicate()
    try:
        out0 = procs[0].communicate(timeout=300)[0]
    except subprocess.TimeoutExpired:
        return fail("survivor did not finish within 300s of the kill")
    print(f"--- survivor (host 0) ---\n{out0}")
    if procs[0].returncode != 0:
        return fail(f"survivor exited {procs[0].returncode}")
    recovery_wall = time.monotonic() - kill_t

    rec = json.load(open(wrec))
    inv = rec.get("serving_invariant") or {}
    got = np.fromfile(os.path.join(outdir, "dist_final.bin"),
                      np.float32).reshape(args.nx, args.ny)
    ref = _reference(args)
    bitwise = got.tobytes() == ref.tobytes()
    ok = (bitwise and rec.get("leg") == "host_loss_recovery"
          and bool(inv.get("ok"))
          and rec.get("lost_hosts") == [1])
    _write_record(
        args.run_record or os.path.join(outdir, "soak_record.json"),
        {"leg": "soak_kill_host", "bitwise_equal": bitwise,
         "recovery_wall_s": recovery_wall,
         "worker_record": rec, "verdict_ok": ok, "outdir": outdir})
    print(f"DIST SOAK kill-host recovered={rec.get('leg')} "
          f"serving_invariant_ok={inv.get('ok')} "
          f"bitwise_equal={bitwise} ok={ok}")
    return 0 if ok else 1


def main(argv=None) -> int:
    args = _args(argv)
    if args.selftest:
        return _selftest(args)
    if args.soak:
        if not args.kill_host:
            print("--soak requires --kill-host (the one soak shape "
                  "so far)")
            return 2
        return _soak_kill_host(args)
    if args.num_processes > 1 and (args.coordinator is None
                                   or args.process_id is None):
        print("multi-process worker needs --coordinator and "
              "--process-id (mpiexec-style)")
        return 2
    return _worker(args)


if __name__ == "__main__":
    sys.exit(main())
