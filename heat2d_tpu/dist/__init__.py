"""Multihost pod runtime — N processes (one per host), one logical
mesh, one serving surface (ROADMAP item 3; docs/DISTRIBUTED.md).

Layers (each a module, each consultable on its own):

- ``runtime``  — bring-up: ``jax.distributed`` rendezvous, the
  ``DistWorld`` topology object, KV-backed bounded barriers and
  heartbeats that turn a dead peer into a named ``HostLostError``
  instead of a hang.
- ``exchange`` — the host-mediated DCN halo route: per-process row
  slabs with T-deep halos over the coordination-service KV store,
  bitwise-equal to the single-process program (the route CI proves
  with real 2-process CPU runs, where cross-process XLA collectives
  are unavailable).
- ``mesh``     — the global 2-axis ('batch', 'xy') device arrangement
  spanning hosts: host-major ordering that keeps the spatial axis
  intra-host, and the DCN-seam profile the scheduler prices.
- ``topology`` — the failure-domain bridge: a host loss presents to
  the fleet supervisor as a process death AND to the mesh scheduler
  as that host's devices quarantined (seq-fenced, reusing
  ``mesh/health.py``), recovered in one transaction under the
  existing ``serving_invariant``.
- ``harness``  — the reusable multi-process spawn/rendezvous/collect
  test harness (the promoted ``test_multihost.py`` capability probe).
- ``cli``      — ``heat2d-tpu-dist``: mpiexec-style worker launch plus
  the ``--selftest`` bitwise-parity and ``--soak --kill-host`` legs
  CI's dist-gate runs.
"""

from heat2d_tpu.dist.runtime import (     # noqa: F401
    DistWorld, Heartbeat, HostLostError, KVBarrier, bring_up,
    elect_recovery_owner, kv_client)
from heat2d_tpu.dist.exchange import (    # noqa: F401
    DcnHaloExchanger, run_process_slab, slab_split)
from heat2d_tpu.dist.topology import (    # noqa: F401
    FailureDomainBridge, PodTopology, pod_monitor)
