"""The reusable multi-process spawn/rendezvous/collect harness — the
``tests/test_multihost.py`` once-per-session capability probe, grown
into a library both the test suite and the ``heat2d-tpu-dist`` driver
legs share.

Two capabilities, probed separately because this platform genuinely
splits them:

- **rendezvous** — ``jax.distributed.initialize`` + the coordination
  service (KV store, barriers, global device enumeration). Works on
  plain CPU builds; the DCN halo route and every dist/ bring-up test
  rides it.
- **collectives** — cross-process XLA computations (shard_map over a
  host-spanning mesh). Some jax builds cannot ("Multiprocess
  computations aren't implemented on the CPU backend") — tests that
  need them SKIP with that exact backend error line, which is what
  ``collectives_unsupported_reason`` extracts.

Each probe runs at most once per session (module-level memo), spawns
REAL processes, and kills-on-timeout with output capture — a probe
must never hang the suite it protects."""

from __future__ import annotations

import os
import re
import socket
import subprocess
import sys
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

#: repo root — children run from here so ``-m heat2d_tpu...`` resolves
REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

#: env vars a parent test session may carry that would distort a
#: child world (device-count forcing, platform pinning)
_STRIP = ("JAX_PLATFORMS", "XLA_FLAGS")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def clean_env(extra: Optional[dict] = None) -> dict:
    """The parent's environment minus the vars that would leak this
    session's platform/device forcing into a child world, plus
    ``extra`` overrides."""
    env = {k: v for k, v in os.environ.items() if k not in _STRIP}
    if extra:
        env.update(extra)
    return env


@dataclass
class ProcResult:
    process_id: int
    returncode: Optional[int]
    output: str          # stdout+stderr, merged

    @property
    def ok(self) -> bool:
        return self.returncode == 0


def first_error_line(outputs: Sequence[str]) -> Optional[str]:
    """The distinguishing ``...Error:...`` line from a failed world's
    merged outputs — the exact backend reason a skip must surface."""
    for out in outputs:
        m = re.search(r"^.*(?:Error|error):.*$", out, re.MULTILINE)
        if m:
            return m.group(0).strip()[:200]
    return None


def spawn_world(num_processes: int,
                argv_fn: Callable[[int, str], List[str]], *,
                env: Optional[dict] = None,
                timeout: float = 180.0,
                cwd: str = REPO) -> List[ProcResult]:
    """Launch ``num_processes`` rendezvousing children and collect
    them: ``argv_fn(process_id, coordinator)`` builds each launch
    line (the mpiexec analogue — same binary, different rank). One
    shared free port becomes ``localhost:<port>``; stdout/stderr are
    merged and captured; a world that outlives ``timeout`` is killed
    whole (never leave half a rendezvous running under the suite).

    Returns per-process results in process-id order. Timeout marks
    returncode None — callers treat that as failure, with whatever
    output made it out."""
    coordinator = f"localhost:{free_port()}"
    env = clean_env() if env is None else env
    procs = [subprocess.Popen(
        argv_fn(i, coordinator), cwd=cwd, env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(num_processes)]
    results: List[ProcResult] = []
    timed_out = False
    for i, p in enumerate(procs):
        try:
            out = p.communicate(
                timeout=None if timed_out else timeout)[0]
            rc: Optional[int] = p.returncode
        except subprocess.TimeoutExpired:
            timed_out = True
            for q in procs:
                if q.poll() is None:
                    q.kill()
            out = p.communicate()[0]
            rc = None
        results.append(ProcResult(i, rc, out or ""))
    return results


# ------------------------------------------------------------------ #
# once-per-session capability probes
# ------------------------------------------------------------------ #

_memo: dict = {}


def rendezvous_unsupported_reason() -> Optional[str]:
    """None when a real 2-process ``jax.distributed`` rendezvous +
    KV round trip works here; otherwise the reason every
    rendezvous-needing test should skip with."""
    if "rendezvous" in _memo:
        return _memo["rendezvous"]
    prog = (
        "import sys, jax\n"
        "jax.distributed.initialize(sys.argv[1], 2, int(sys.argv[2]))\n"
        "from jax._src import distributed\n"
        "c = distributed.global_state.client\n"
        "c.key_value_set('probe/%s' % sys.argv[2], 'up')\n"
        "peer = '10'[int(sys.argv[2])]\n"
        "assert c.blocking_key_value_get('probe/' + peer, 60000) == 'up'\n"
        "print('RENDEZVOUS_OK', jax.process_count())\n")
    results = spawn_world(
        2, lambda i, coord: [sys.executable, "-c", prog, coord, str(i)],
        env=clean_env({"JAX_PLATFORMS": "cpu"}), timeout=120)
    if all(r.ok for r in results):
        _memo["rendezvous"] = None
    else:
        _memo["rendezvous"] = (
            first_error_line([r.output for r in results])
            or f"rendezvous probe exited "
               f"{[r.returncode for r in results]}")
    return _memo["rendezvous"]


def collectives_unsupported_reason() -> Optional[str]:
    """None when this harness can run a real 2-process cross-process
    XLA computation (a minimal dist2d step over a (2, 1) host-spanning
    mesh); otherwise the exact backend error line — e.g.
    ``XlaRuntimeError: ... Multiprocess computations aren't
    implemented on the CPU backend`` — that the multihost test file
    skips with (green-or-skipped, never silently red)."""
    if "collectives" in _memo:
        return _memo["collectives"]
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        results = spawn_world(
            2, lambda i, coord: [
                sys.executable, "-m", "heat2d_tpu.cli",
                "--mode", "dist2d", "--gridx", "2", "--gridy", "1",
                "--nxprob", "8", "--nyprob", "8", "--steps", "1",
                "--platform", "cpu", "--host-device-count", "1",
                "--coordinator", coord,
                "--num-processes", "2", "--process-id", str(i),
                "--dat-layout", "none", "--outdir", td],
            timeout=180)
    if all(r.ok for r in results):
        _memo["collectives"] = None
    elif any(r.returncode is None for r in results):
        _memo["collectives"] = "2-process probe timed out after 180s"
    else:
        _memo["collectives"] = (
            first_error_line([r.output for r in results])
            or f"probe exited {[r.returncode for r in results]} with "
               f"no recognizable error line")
    return _memo["collectives"]
