"""The global 2-axis ('batch', 'xy') mesh spanning hosts.

The single-process mesh engines (mesh/runner.py batch route, the
PR 7 fused-halo spatial route) consume a flat DEVICE ORDER and build
their own meshes — so the pod layer's whole job is to hand them the
RIGHT order: host-major, so the 'xy' (spatial, halo-ppermute) axis
stays inside one host wherever the shape allows and only the 'batch'
axis crosses DCN. SNIPPETS.md [2]'s "8-chip to 6000-chip without
changing application code" pattern is exactly this: the application
never learns the pod exists, the arrangement does.

``seam_profile`` prices what the arrangement could not avoid: for a
(batch, xy) grid it walks every xy-adjacent pair (ring closure
included — the fused route's halo ppermute is a ring) and classifies
each seam via ``DistWorld.link_kind``; the scheduler folds the
resulting DCN/ICI seam counts and per-step bytes into its decision
rows (mesh/scheduler.py), and tune/measure.py's link model prices the
same asymmetry for depth tuning."""

from __future__ import annotations

from typing import List, Optional, Sequence

from heat2d_tpu.dist.runtime import DistWorld


def pod_device_order(world: DistWorld) -> List[int]:
    """Global device ordinals, host-major (process-major), stable
    within a host — the flat order every existing runner consumes."""
    return [g for p in range(world.process_count)
            for g in world.devices_of(p)]


def arrange_pod(world: DistWorld, batch: int, xy: int) -> List[List[int]]:
    """Host-major order reshaped (batch, xy): with uniform per-host
    device counts and ``xy`` dividing them (or them dividing ``xy``),
    every xy-row touches as few hosts as possible, so halo traffic
    stays ICI and only batch dispatch crosses DCN."""
    order = pod_device_order(world)
    if batch * xy != len(order):
        raise ValueError(
            f"({batch}, {xy}) mesh wants {batch * xy} devices, the "
            f"pod has {len(order)}")
    return [order[r * xy:(r + 1) * xy] for r in range(batch)]


def seam_profile(world: DistWorld, arrangement: Sequence[Sequence[int]],
                 ny: int, itemsize: int = 4) -> dict:
    """Classify every xy-adjacent device pair (including the ring
    wrap) and price the per-step halo edge traffic:

    - ``ici_seams`` / ``dcn_seams`` — seam counts by link class;
    - ``seam_bytes_per_step`` — 2·ny·itemsize per seam (one strip
      each way, the fused route's per-step edge traffic);
    - ``dcn_bytes_per_step`` — the share crossing hosts, the number
      the scheduler prices against the DCN link bandwidth.
    """
    counts = {"ici": 0, "dcn": 0}
    per_seam = 2 * ny * itemsize
    dcn_bytes = 0
    for row in arrangement:
        k = len(row)
        if k < 2:
            continue
        for j in range(k):
            a, b = row[j], row[(j + 1) % k]
            if a == b:
                continue
            kind = world.link_kind(a, b)
            kind = "ici" if kind == "local" else kind
            counts[kind] += 1
            if kind == "dcn":
                dcn_bytes += per_seam
    total = counts["ici"] + counts["dcn"]
    return {"ici_seams": counts["ici"], "dcn_seams": counts["dcn"],
            "seam_bytes_per_step": per_seam * total,
            "dcn_bytes_per_step": dcn_bytes}


def pod_mesh(world: Optional[DistWorld] = None,
             batch: Optional[int] = None, xy: Optional[int] = None):
    """A real ``jax.sharding.Mesh`` with axes ('batch', 'xy') over the
    pod-aware device order. Defaults: the whole world, all devices on
    'batch' ('xy'=1 — the safe shape everywhere; spatial shapes are
    the scheduler's call). Requires a backend that can actually run
    cross-process computations — the CPU CI backend cannot, which is
    exactly what dist/harness.py's capability probe reports."""
    import jax

    if world is None:
        world = DistWorld.from_env()
    devs = jax.devices()
    if batch is None or xy is None:
        batch, xy = len(devs), 1
    import numpy as np

    grid = np.array(
        [[devs[g] for g in row]
         for row in arrange_pod(world, batch, xy)], dtype=object)
    return jax.sharding.Mesh(grid, ("batch", "xy"))
