// Native text-grid formatter for heat2d-tpu.
//
// The reference's I/O layer is native C stdio (prtdat, mpi_heat2Dn.c:253-268;
// the readfloat binary->text conversion loop, grad1612_mpi_heat.c:191-203).
// This library is its TPU-framework counterpart: the same printf("%6.1f")
// byte format, vectorized over whole grids, callable from Python via ctypes
// (heat2d_tpu/native/lib.py). Python's format(v, '6.1f') produces identical
// bytes; this path exists because per-value Python formatting is the
// bottleneck when dumping large grids (the reference dumps 2560x2048 .dat
// files), and because the build mandate keeps the runtime's native layers
// native.
//
// Build: make -C heat2d_tpu/native   (g++ -O2 -shared -fPIC)

#include <cstdio>
#include <cstring>

extern "C" {

// Row-major layout (grad1612 writers): every value "%6.1f " with trailing
// space, newline per row. Returns bytes written, or -1 if cap too small.
long heat2d_format_rowmajor(const float* u, long nx, long ny,
                            char* out, long cap) {
    long w = 0;
    for (long i = 0; i < nx; ++i) {
        for (long j = 0; j < ny; ++j) {
            if (cap - w < 64) return -1;
            int n = snprintf(out + w, cap - w, "%6.1f ",
                             static_cast<double>(u[i * ny + j]));
            if (n < 0) return -1;
            w += n;
        }
        if (cap - w < 2) return -1;
        out[w++] = '\n';
    }
    return w;
}

// Baseline layout (mpi_heat2Dn.c prtdat): lines iterate the y index
// descending, x across; single space *between* values, none trailing.
long heat2d_format_baseline(const float* u, long nx, long ny,
                            char* out, long cap) {
    long w = 0;
    for (long iy = ny - 1; iy >= 0; --iy) {
        for (long ix = 0; ix < nx; ++ix) {
            if (cap - w < 64) return -1;
            int n = snprintf(out + w, cap - w, "%6.1f",
                             static_cast<double>(u[ix * ny + iy]));
            if (n < 0) return -1;
            w += n;
            out[w++] = (ix != nx - 1) ? ' ' : '\n';
        }
    }
    return w;
}

}  // extern "C"
