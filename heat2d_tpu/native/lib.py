"""ctypes loader for the native text-grid formatter (heat2d_io.cpp).

Builds the shared library on first use if a compiler is available (the
environment has no pybind11; plain ctypes over an extern-C ABI keeps the
binding dependency-free). Callers treat any failure here as "no native
path" and fall back to pure Python — the two paths are byte-identical
(tests/test_native.py proves it against the C formatter directly).
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libheat2d_io.so")


class _NativeIO:
    def __init__(self, cdll: ctypes.CDLL):
        self._lib = cdll
        for name in ("heat2d_format_rowmajor", "heat2d_format_baseline"):
            fn = getattr(cdll, name)
            fn.restype = ctypes.c_long
            fn.argtypes = [ctypes.POINTER(ctypes.c_float), ctypes.c_long,
                           ctypes.c_long, ctypes.c_char_p, ctypes.c_long]

    def _format(self, fn_name: str, a: np.ndarray) -> str:
        a = np.ascontiguousarray(a, dtype=np.float32)
        nx, ny = a.shape
        cap = nx * ny * 24 + nx + 64
        buf = ctypes.create_string_buffer(cap)
        n = getattr(self._lib, fn_name)(
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            nx, ny, buf, cap)
        if n < 0:
            raise RuntimeError(f"{fn_name}: buffer too small (cap={cap})")
        return buf.raw[:n].decode("ascii")

    def format_rowmajor(self, a) -> str:
        return self._format("heat2d_format_rowmajor", a)

    def format_baseline(self, a) -> str:
        return self._format("heat2d_format_baseline", a)


def _build() -> bool:
    cxx = shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        return False
    try:
        subprocess.run(
            [cxx, "-O2", "-Wall", "-fPIC", "-shared",
             os.path.join(_DIR, "heat2d_io.cpp"), "-o", _SO],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def load() -> _NativeIO:
    """Load (building if needed) the native formatter; raises on failure."""
    if not os.path.exists(_SO) and not _build():
        raise ImportError("native heat2d_io library unavailable "
                          "(no compiler or build failed)")
    return _NativeIO(ctypes.CDLL(_SO))
