"""Configuration surface for heat2d-tpu.

Mirrors the reference's compile-time ``#define`` knob census
(mpi_heat2Dn.c:29-44, grad1612_mpi_heat.c:5-21, grad1612_hybrid_heat.c:6-24,
grad1612_cuda_heat.cu:6-13 — see SURVEY.md §5.6) as one runtime dataclass:
every knob keeps the reference's name and default, but changing it no longer
means recompiling a C program.

Validation reproduces the reference's startup checks:
- worker-count range 3..8 for the baseline master/worker mode
  (mpi_heat2Dn.c:72-78),
- GRIDX*GRIDY == device count and divisibility NXPROB%GRIDX == 0,
  NYPROB%GRIDY == 0 for the 2D SPMD mode (grad1612_mpi_heat.c:54-64),
raised as ``ConfigError`` instead of ``MPI_Abort`` (with an *initialized*
error code, unlike mpi_heat2Dn.c:76 — SURVEY.md A.5).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from heat2d_tpu import vocab as _vocab


class ConfigError(ValueError):
    """Invalid solver configuration (the framework's MPI_Abort analogue)."""


#: Execution modes — one engine, pluggable modes, replacing the reference's
#: four copy-pasted programs (SURVEY.md §7.1):
#:   serial  — pure jnp golden model, single device  (serial 1/1 runs)
#:   pallas  — Pallas/Mosaic TPU kernel, single chip  (grad1612_cuda_heat.cu)
#:   dist1d  — 1D row-strip sharding, N/S halo        (mpi_heat2Dn.c)
#:   dist2d  — 2D block sharding, 4-neighbor halo     (grad1612_mpi_heat.c)
#:   hybrid  — 2D block sharding with the Pallas kernel per shard
#:             (grad1612_hybrid_heat.c: MPI across chips + intra-chip tiling)
MODES = ("serial", "pallas", "dist1d", "dist2d", "hybrid")

#: Halo-exchange routes for the distributed modes:
#:   collective — the existing lax.ppermute exchange followed by the
#:                shard chunk (a collective barrier per chunk of T steps).
#:   fused      — overlap route: edge-strip communication runs WHILE the
#:                interior stencil sweep advances (the reference's
#:                persistent-nonblocking-MPI inner/boundary split,
#:                grad1612_mpi_heat.c:233-259). On TPU with async remote
#:                copies the exchange moves INTO the Pallas kernel
#:                (pltpu.make_async_remote_copy, docs/SCALING.md);
#:                elsewhere the overlap schedule runs as a ppermute +
#:                interior/frame split. Degrades automatically to the
#:                collective route where neither applies (deep halos,
#:                1-wide shards, band-streamed shards) — selecting
#:                "fused" never fails, it only ever falls back.
HALO_ROUTES = ("collective", "fused")

#: Time-stepping schemes (docs/ALGORITHMS.md):
#:   explicit — the reference's forward-Euler 5-point update; fastest
#:              per step but stability-limited (cx + cy <= 1/2,
#:              ops/stability.py), so t_final costs O(1/dx^2) steps.
#:   adi      — Crank-Nicolson ADI (Peaceman-Rachford) on batched
#:              tridiagonal Thomas solves (ops/tridiag.py):
#:              unconditionally stable, O(dt^2) — dt chosen by
#:              accuracy, typically 100-1000x fewer steps to the same
#:              physical time.
#:   mg       — unsplit Crank-Nicolson solved per step by geometric
#:              multigrid V-cycles (ops/multigrid.py): no splitting
#:              error; the iterative route for steady/convergence
#:              solves.
#: Derived from the single-source vocabulary (vocab.py) so this list,
#: diff/vocab.METHODS, and serve.schema.SUPPORTED_METHODS cannot
#: drift independently (the R005-style drift class).
TIME_METHODS = _vocab.TIME_METHODS

#: Problem families (the spatial-operator axis — heat2d_tpu/problems/,
#: docs/PROBLEMS.md). "heat5" is the reference's 5-point operator and
#: keeps every pre-registry program byte-identical (jaxpr-pinned).
PROBLEMS = _vocab.PROBLEMS


@dataclasses.dataclass(frozen=True)
class HeatConfig:
    # -- shared knobs (grad1612_mpi_heat.c:5-21) ----------------------------
    nxprob: int = 10          # NXPROB — x dimension of problem grid
    nyprob: int = 10          # NYPROB — y dimension of problem grid
    steps: int = 100          # STEPS  — number of time steps
    cx: float = 0.1           # CX     — x diffusivity coefficient
    cy: float = 0.1           # CY     — y diffusivity coefficient
    debug: bool = False       # DEBUG  — extra messages

    # -- decomposition (grad1612_mpi_heat.c:10-12) --------------------------
    gridx: int = 1            # GRIDX — process-grid extent along x (rows)
    gridy: int = 1            # GRIDY — process-grid extent along y (cols)
    reorganisation: bool = True  # REORGANISATION — let the runtime reorder
    # ranks (MPI_Cart_create reorder flag). For the TPU mesh this is purely
    # informational: device order is chosen by jax.make_mesh for ICI locality.

    # -- convergence (grad1612_mpi_heat.c:14-16) ----------------------------
    convergence: bool = False  # CONVERGENCE — early-exit on residual
    interval: int = 20         # INTERVAL — steps between residual checks
    sensitivity: float = 0.1   # SENSITIVITY — residual threshold (EPSILON)

    # -- execution ----------------------------------------------------------
    mode: str = "serial"
    # Time-stepping scheme (TIME_METHODS). "explicit" keeps every
    # pre-existing route byte-identical (jaxpr-pinned); the implicit
    # schemes are unconditionally stable and skip the stability box.
    method: str = "explicit"
    # Problem family (PROBLEMS — the spatial operator). The default
    # "heat5" is the reference operator and leaves every pre-registry
    # program byte-identical (jaxpr-pinned); other families validate
    # their own stability bound and capability matrix
    # (heat2d_tpu/problems/base.py).
    problem: str = "heat5"
    # Wide-halo depth T for the distributed modes: each halo exchange
    # carries a T-deep ghost ring and the shard advances T steps locally
    # per exchange — 4 ppermutes per T steps instead of 4T (the distributed
    # analogue of the Pallas temporal blocking). None = auto (8, clamped to
    # the shard size). 1 reproduces the reference's per-step exchange.
    halo_depth: Optional[int] = None
    # Halo-exchange route for the distributed modes (HALO_ROUTES):
    # "collective" keeps the existing exchange-then-compute schedule
    # (byte-identical program to builds before the fused route existed —
    # jaxpr-pinned); "fused" overlaps edge communication with interior
    # compute, bitwise-identical results, degrading to collective
    # wherever the overlap geometry or backend support is missing.
    halo: str = "collective"
    # f64 accumulation mirrors the C reference's promotion of the f32 stencil
    # through double (literals 0.1/2.0 — SURVEY.md Appendix B); f32 is the
    # TPU-fast path. Storage is always float32, as in the reference.
    accum_dtype: str = "float32"   # "float32" | "float64"
    # Kernel step form for the pallas/hybrid modes. Default: the FMA
    # factoring (1-2cx-2cy)*c + cx*(N+S) + cy*(E+W) — ~24% faster on the
    # VPU, f32-ulp from the literal form. True: the literal reference
    # expression c + cx*(N+S-2c) + cy*(E+W-2c)
    # (grad1612_cuda_heat.cu:59-61), making kernel results BITWISE
    # identical to serial mode — the verification escape hatch the parity
    # tests pin. (serial/dist1d/dist2d always use the literal form.)
    bitwise_parity: bool = False

    # -- baseline-mode knobs (mpi_heat2Dn.c:32-33) --------------------------
    # Number of row-strip shards for dist1d. The reference requires 3..8
    # workers; we validate the same range only when `strict_baseline` is on.
    numworkers: Optional[int] = None
    strict_baseline: bool = False

    # ------------------------------------------------------------------ #

    def __post_init__(self):
        if self.mode not in MODES:
            raise ConfigError(
                f"mode must be one of {MODES}, got {self.mode!r}")
        if self.nxprob < 3 or self.nyprob < 3:
            raise ConfigError(
                f"grid must be at least 3x3 to have interior cells, got "
                f"{self.nxprob}x{self.nyprob}")
        if self.steps < 0:
            raise ConfigError(f"steps must be >= 0, got {self.steps}")
        if self.accum_dtype not in ("float32", "float64"):
            raise ConfigError(
                "accum_dtype must be float32 or float64, got "
                f"{self.accum_dtype!r}")
        if self.gridx < 1 or self.gridy < 1:
            raise ConfigError("gridx/gridy must be >= 1")
        if self.mode in ("dist2d", "hybrid"):
            # grad1612_mpi_heat.c:60-64 divisibility validation
            if self.nxprob % self.gridx or self.nyprob % self.gridy:
                raise ConfigError(
                    f"ERROR: ({self.nxprob}/{self.gridx}) or "
                    f"({self.nyprob}/{self.gridy}) is not an integer")
        if self.mode == "dist1d":
            nw = self.numworkers or self.gridx
            if self.strict_baseline and not (3 <= nw <= 8):
                # mpi_heat2Dn.c:72-78 (MINWORKER=3, MAXWORKER=8)
                raise ConfigError(
                    "ERROR: the number of tasks must be between 4 and 9.")
            # Uneven strips are allowed, as in the reference (averow/extra,
            # mpi_heat2Dn.c:89-94): the engine pads to equal shards and the
            # pad rows sit inert outside the boundary mask.
        if self.convergence and self.interval < 1:
            raise ConfigError("interval must be >= 1 when convergence is on")
        if self.halo_depth is not None and self.halo_depth < 1:
            raise ConfigError("halo_depth must be >= 1 (or None for auto)")
        if self.halo not in HALO_ROUTES:
            raise ConfigError(
                f"halo must be one of {HALO_ROUTES}, got {self.halo!r}")
        if self.method not in TIME_METHODS:
            raise ConfigError(
                f"method must be one of {TIME_METHODS}, got "
                f"{self.method!r}")
        if self.problem not in PROBLEMS:
            raise ConfigError(
                f"problem must be one of {PROBLEMS}, got "
                f"{self.problem!r}")
        if self.problem != _vocab.DEFAULT_PROBLEM:
            # Registry families: per-family capability matrix + grid
            # floor + stability bound (heat2d_tpu/problems/base.py).
            # The heat5 branch below is the pre-registry code path,
            # byte-for-byte — the jaxpr pins hold it.
            from heat2d_tpu.problems.base import spec_for
            spec = spec_for(self.problem)
            if self.mode != "serial":
                raise ConfigError(
                    f"problem {self.problem!r} runs mode 'serial' "
                    f"only in the solver (the pallas/distributed "
                    f"modes are built for the heat5 operator; use "
                    f"the ensemble/serve path for batched kernel "
                    f"routes) — got mode {self.mode!r}")
            ok, reason = spec.supports_method(self.method)
            if not ok:
                raise ConfigError(reason)
            if min(self.nxprob, self.nyprob) < spec.min_grid:
                raise ConfigError(
                    f"problem {self.problem!r} (halo width "
                    f"{spec.halo_width}) needs a grid of at least "
                    f"{spec.min_grid}x{spec.min_grid} for interior "
                    f"cells, got {self.nxprob}x{self.nyprob}")
            if self.method == "explicit":
                from heat2d_tpu.ops.stability import (
                    check_problem_stability)
                check_problem_stability(self.problem, self.cx,
                                        self.cy,
                                        where="explicit scheme")
        elif self.method == "explicit":
            # Explicit routes validate against the stability box; the
            # implicit routes skip it by design (ops/stability.py).
            from heat2d_tpu.ops.stability import (
                check_explicit_stability)
            check_explicit_stability(self.cx, self.cy,
                                     where="explicit scheme")
        elif self.mode not in ("serial", "pallas"):
            raise ConfigError(
                f"method {self.method!r} runs single-device modes "
                f"(serial/pallas) only; distributed implicit sweeps "
                f"are not built yet — got mode {self.mode!r}")

    # Convenience views ------------------------------------------------- #

    @property
    def shape(self) -> tuple[int, int]:
        return (self.nxprob, self.nyprob)

    @property
    def xcell(self) -> int:
        """Per-shard rows in the 2D decomposition (grad1612_mpi_heat.c:47)."""
        return self.nxprob // self.gridx

    @property
    def ycell(self) -> int:
        """Per-shard cols in the 2D decomposition (grad1612_mpi_heat.c:48)."""
        return self.nyprob // self.gridy

    @property
    def n_shards(self) -> int:
        if self.mode == "dist1d":
            return self.numworkers or self.gridx
        return self.gridx * self.gridy

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "HeatConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    def replace(self, **kw) -> "HeatConfig":
        return dataclasses.replace(self, **kw)


# Reference per-program defaults, for parity runs and tests.

#: mpi_heat2Dn.c:29-31 — 10x10 grid, 100 steps.
BASELINE_DEFAULTS = dict(nxprob=10, nyprob=10, steps=100)

#: grad1612_cuda_heat.cu:6-8 — 640x1024 grid, 10000 steps.
CUDA_DEFAULTS = dict(nxprob=640, nyprob=1024, steps=10000)
