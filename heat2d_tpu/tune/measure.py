"""Measurement library — the two-point marginal-step-time protocol.

One copy of the protocol the repo previously duplicated:

- ``min_of_two_point`` — fixed-span, min-of-reps marginal (the
  ``tune_bands.py`` probe protocol; spans per the round-4 noise study:
  >= 1.2 s marginal windows repeat within ~1-3%).
- ``two_point_estimate`` — the adaptive, cross-decade-confirmed
  estimator (``benchmarks/sweep.py``'s protocol; moved here verbatim,
  sweep imports it back).

Plus the pieces a *search* needs that the hand-run harnesses skipped:

- ``probe_limits`` — probe mode as a context manager: lifts the VMEM
  hard limit so the search can measure past the fast-fail estimate, and
  RESTORES it on any exit path (the old harnesses assigned the module
  global and never restored it on exception, leaving the process with a
  10^9-byte "limit").
- ``measure_candidate`` — one search point end to end: compile-wall
  guard, failure-class capture (``oom`` vs ``compile_error`` vs
  ``timeout`` vs ``error``) instead of a crashed sweep, and ``tune_*``
  metrics through an optional obs registry.
- ``SimulatedBackend`` — a deterministic analytic step-time model (HBM
  stream + halo recompute + pad tax, with the envelope failure modes)
  so the whole search/db/resume loop runs on CPU CI in milliseconds.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Optional

from heat2d_tpu.tune.space import Candidate, Problem, band_est_bytes

#: Absolute dt floor for the adaptive estimator: fence variance through
#: the tunnel reaches tens of ms, so a smaller window can be pure noise
#: even when it clears 5x the *measured* jitter.
NOISE_FLOOR_S = 0.05

#: Two marginal estimates a decade apart must agree within this factor
#: for either to be believed (see two_point_estimate).
AGREE_FACTOR = 1.5

#: Per-direction link bandwidths by class (DistWorld.link_kind
#: vocabulary — docs/DISTRIBUTED.md link table). ICI is the
#: v5e-class order of magnitude the fused-route model always used;
#: DCN is the per-host share of a pod's data-center fabric — the
#: ~7x asymmetry is the POINT: a route that hides its edge traffic
#: under ICI may be bandwidth-bound over DCN, so depth/route tuning
#: and the scheduler's seam pricing must see which class a seam
#: crosses.
LINK_BYTES_PER_S = {"ici": 45e9, "dcn": 6.25e9}


def link_bytes_per_s(kind: str) -> float:
    """Bandwidth of a link CLASS ('local' prices as HBM — on-chip
    traffic is the kernel's own stream, not a seam)."""
    if kind == "local":
        return SimulatedBackend.HBM_BYTES_PER_S
    try:
        return LINK_BYTES_PER_S[kind]
    except KeyError:
        raise ValueError(
            f"unknown link kind {kind!r}; expected 'local' or one of "
            f"{sorted(LINK_BYTES_PER_S)}") from None


def two_point_estimate(timed_run, lo, hi0, max_hi,
                       floor=NOISE_FLOOR_S, agree=AGREE_FACTOR):
    """Adaptive two-point marginal step time: (step_time|None, hi, result).

    ``timed_run(n)`` runs n steps and returns an object with ``.elapsed``.
    The marginal is (t_hi - t_lo)/(hi - lo) with the fixed fence overhead
    cancelled, hi growing x10 until the window clears the jitter floor.

    Round 2's committed chip sweep carried a physically impossible row
    (pallas 320x256 at 241.9 Mcells/s — 122x slower than serial on the
    same grid): a single lucky jitter spike in t_hi can clear any static
    threshold and produce a confidently wrong marginal. Hence the
    CONFIRMATION rule: a candidate is only accepted once the estimate
    from the next decade agrees within ``agree``x — a jitter spike can
    clear the floor once, but it cannot produce the same wrong marginal
    at 10x the step count, because the spike's contribution to the
    marginal shrinks 10x while the true signal stays put. At ``max_hi``
    (no further decade available) an unconfirmed candidate is accepted
    only if its window also clears 2x the absolute floor — at the
    reference's own 100k-iteration amortization span (Report.pdf p.26)
    noise cannot fake a 100 ms window.
    """
    lo_ts = sorted(timed_run(lo).elapsed for _ in range(3))
    t_lo = lo_ts[0]
    # Spread of the two best of three: one outlier sample can no longer
    # fake a tiny jitter estimate (or poison t_lo).
    jitter = lo_ts[1] - lo_ts[0]
    prev = None
    hi = hi0
    while True:
        ra, rb = timed_run(hi), timed_run(hi)
        result = ra if ra.elapsed <= rb.elapsed else rb
        dt = result.elapsed - t_lo
        cand = dt / (hi - lo) if dt > max(5 * jitter, floor) else None
        if cand is not None and prev is not None:
            if max(cand, prev) <= agree * min(cand, prev):
                return cand, hi, result      # confirmed across a decade
        if hi >= max_hi:
            if cand is not None and dt > max(5 * jitter, 2 * floor):
                return cand, hi, result      # fully amortized window
            return None, hi, result
        prev = cand
        hi = min(hi * 10, max_hi)


def min_of_two_point(fn, u, lo: int, hi: int, reps: int = 4) -> float:
    """Fixed-span two-point marginal step time of ``fn(u, n)``,
    min-of-``reps`` at each point. One warmup per step count covers
    compile + program load; the reps run warmup-free."""
    from heat2d_tpu.utils.timing import timed_call

    def min_of(n):
        ts = [timed_call(fn, u, n)[1]]          # warms up once
        ts += [timed_call(fn, u, n, warmup=False)[1]
               for _ in range(reps - 1)]
        return min(ts)

    return (min_of(hi) - min_of(lo)) / (hi - lo)


@contextlib.contextmanager
def probe_limits(origin: str = "lifted by the tune probe"):
    """Probe mode: lift the VMEM hard limit so measurements can reach
    past the fast-fail estimate (the envelope is what a probe exists to
    measure), stamping the origin so a fast-fail inside the probe
    reports itself as probe-lifted rather than as a --vmem-budget
    override. Always restores the previous limit/origin/source — the
    old harness-global assignment leaked probe mode into the rest of
    the process on any exception."""
    from heat2d_tpu.ops import pallas_stencil as ps

    # Flush the lazy HEAT2D_VMEM_BUDGET application BEFORE saving state:
    # otherwise the first budget query inside the probe would apply the
    # env override mid-probe (silently un-lifting the hard limit), and
    # the restore below would then revert the env's limit while leaving
    # its budget applied — inconsistent provenance (review r6).
    ps._maybe_env_budget()
    saved = (ps.VMEM_HARD_LIMIT_BYTES, ps.VMEM_LIMIT_ORIGIN,
             ps.VMEM_BUDGET_SOURCE)
    ps.VMEM_HARD_LIMIT_BYTES = 10 ** 9
    ps.VMEM_LIMIT_ORIGIN = origin
    ps.VMEM_BUDGET_SOURCE = "probe"
    try:
        yield
    finally:
        (ps.VMEM_HARD_LIMIT_BYTES, ps.VMEM_LIMIT_ORIGIN,
         ps.VMEM_BUDGET_SOURCE) = saved


# --------------------------------------------------------------------- #
# Failure classification
# --------------------------------------------------------------------- #

#: Terminal point statuses a resumed search never re-measures. "error"
#: is deliberately NOT terminal: an unclassified transient (a wedged
#: tunnel, a spurious runtime fault) deserves a retry on the next run.
TERMINAL_STATUSES = ("ok", "oom", "compile_error", "timeout", "pruned")


def classify_failure(exc: BaseException) -> str:
    """Map a measurement exception to a failure class: the search wants
    'this config cannot work here' (oom / compile_error) separated from
    'this run hiccuped' (error — retried on resume)."""
    from heat2d_tpu.config import ConfigError

    text = f"{type(exc).__name__}: {exc}"
    if isinstance(exc, (SimulatedOOM, ConfigError)):
        # ConfigError here is the VMEM working-set fast-fail (probe
        # mode lifts the limit, but a caller may measure unlifted).
        return "oom"
    if ("RESOURCE_EXHAUSTED" in text or "scoped vmem" in text.lower()
            or "vmem" in text.lower() and "exceed" in text.lower()):
        return "oom"
    if isinstance(exc, SimulatedCompileError):
        return "compile_error"
    if ("Mosaic" in text or "lowering" in text.lower()
            or "INTERNAL" in text or "UNIMPLEMENTED" in text
            or "XlaRuntimeError" in text):
        return "compile_error"
    return "error"


@dataclasses.dataclass
class MeasureOutcome:
    """One measured search point."""
    candidate: Candidate
    status: str                       # ok|oom|compile_error|timeout|error
    step_time_s: Optional[float] = None
    mcells_per_s: Optional[float] = None
    warmup_s: Optional[float] = None
    error: Optional[str] = None

    def to_point(self) -> dict:
        """The db row for this outcome (space knobs + result)."""
        d = {"route": self.candidate.route, "bm": self.candidate.bm,
             "tsteps": self.candidate.tsteps, "status": self.status}
        if self.step_time_s is not None:
            d["step_time_s"] = self.step_time_s
            d["mcells_per_s"] = self.mcells_per_s
        if self.warmup_s is not None:
            d["warmup_s"] = round(self.warmup_s, 3)
        if self.error:
            d["error"] = self.error[:200]
        return d


# --------------------------------------------------------------------- #
# Real-device measurement
# --------------------------------------------------------------------- #

def _legacy_chunk_fn(bm: int, t: int, cx: float, cy: float):
    """A band_chunk mirror pinned to the LEGACY kernel-C route even
    where band_chunk would route to C2: pad ONCE outside the sweep loop
    (domain_rows carries the true row count) — a naive per-call
    band_multi_step(bm=bm) re-pads and re-slices every sweep at
    non-divisor bm, inflating exactly the kernel-C rows a forced-legacy
    measurement exists to compare fairly."""
    import jax
    import jax.numpy as jnp

    from heat2d_tpu.ops import pallas_stencil as ps

    def chunk(v, n):
        nx_dom = v.shape[0]
        _, m_pad = ps._resolve_bands(nx_dom, v.shape[1], v.dtype, bm)
        if m_pad > nx_dom:
            v = jnp.pad(v, ((0, m_pad - nx_dom), (0, 0)))
        full, rem = divmod(n, t)
        if full:
            v = jax.lax.fori_loop(
                0, full,
                lambda _, w: ps.band_multi_step(
                    w, t, cx, cy, bm=bm, domain_rows=nx_dom),
                v, unroll=False)
        if rem:
            v = ps.band_multi_step(v, rem, cx, cy, bm=bm,
                                   domain_rows=nx_dom)
        return v[:nx_dom]

    return jax.jit(chunk, static_argnums=1)


def measure_band_point(u, bm: int, t: int, lo: int = 4000,
                       hi: int = 20000, reps: int = 4,
                       force_legacy: bool = False,
                       cx: float = 0.1, cy: float = 0.1) -> float:
    """Marginal step time of one (bm, T) band config on the attached
    device — the tune_bands.py probe measurement, as a library call.
    ``force_legacy`` measures kernel C even where band_chunk would
    route to C2."""
    import jax

    from heat2d_tpu.ops import pallas_stencil as ps

    if force_legacy:
        fn = _legacy_chunk_fn(bm, t, cx, cy)
    else:
        fn = jax.jit(
            lambda v, n: ps.band_chunk(v, n, cx, cy, tsteps=t, bm=bm),
            static_argnums=1)
    return min_of_two_point(fn, u, lo, hi, reps=reps)


def _fused_mesh_fn(problem: Problem, t: int):
    """(runner, u0) measuring the fused halo route on the ATTACHED
    device mesh: the problem shape is the per-SHARD block, the global
    grid spans a near-square mesh of every visible device, and the
    runner is the sharded fused-route program at overlap depth ``t``
    (static steps ride through make_local_multi's chunk schedule).
    Needs >= 2 devices — there is no halo to overlap on one."""
    import jax

    from heat2d_tpu.config import ConfigError, HeatConfig
    from heat2d_tpu.parallel import sharded as sh
    from heat2d_tpu.parallel.mesh import make_mesh

    from heat2d_tpu.parallel.scaling import square_mesh

    devs = jax.devices()
    if len(devs) < 2:
        raise ConfigError(
            "fused halo route needs >= 2 attached devices to measure "
            "(no neighbor, no exchange to overlap)")
    gx, gy = square_mesh(len(devs))
    cfg = HeatConfig(nxprob=problem.nx * gx, nyprob=problem.ny * gy,
                     steps=1, mode="dist2d", gridx=gx, gridy=gy,
                     halo="fused", halo_depth=t)
    mesh = make_mesh(gx, gy)
    multi = sh.make_local_multi(cfg, mesh)
    spec = jax.sharding.PartitionSpec("x", "y")
    runners = {}

    def fn(u, n):
        # The step count is STATIC (baked into the chunk schedule), so
        # it must close over the shard_map'd program, not ride through
        # it as an operand — one compiled runner per distinct n, reused
        # across the timing reps (the make_sharded_runner pattern).
        if n not in runners:
            mapped = sh.shard_map_compat(
                lambda v, n=n: multi(v, n), mesh,
                in_specs=spec, out_specs=spec, check_vma=False)
            runners[n] = jax.jit(mapped)
        return runners[n](u)

    u0 = jax.block_until_ready(sh.sharded_inidat(cfg, mesh))
    return fn, u0, gx * gy


def _measure_real(u, problem: Problem, cand: Candidate, *, lo, hi, reps,
                  compile_timeout_s) -> MeasureOutcome:
    import jax

    from heat2d_tpu.ops import pallas_stencil as ps
    from heat2d_tpu.utils.timing import timed_call

    if cand.route == "fused":
        fn, u, ndev = _fused_mesh_fn(problem, cand.tsteps)
        # Same compile-wall guard as every other route: an n-device
        # mesh program is exactly the compile most likely to blow it,
        # and a blown wall must record as 'timeout' so resume never
        # pays it again.
        first = timed_call(fn, u, lo)
        warmup = first.warmup_s
        if compile_timeout_s is not None and warmup is not None \
                and warmup > compile_timeout_s:
            return MeasureOutcome(
                cand, "timeout", warmup_s=warmup,
                error=f"compile+warmup {warmup:.1f}s over the "
                      f"{compile_timeout_s:.0f}s wall")
        step = min_of_two_point(fn, u, lo, hi, reps=reps)
        # Global rate over the whole mesh; the db entry stays keyed by
        # the per-shard shape the runtime hook looks up.
        return MeasureOutcome(
            cand, "ok", step_time_s=step,
            mcells_per_s=problem.cells * ndev / step / 1e6,
            warmup_s=warmup)
    if cand.route in ("adi", "adi_s"):
        # Per-ADI-step marginal: an ADI step costs ~an order of
        # magnitude more than an explicit sweep, so the span scales
        # down; the datum is comparable only within the adi: frontier
        # (its own db key namespace).
        import jax.numpy as jnp

        from heat2d_tpu.ops import tridiag as tdk

        variant = "strided" if cand.route == "adi_s" else "xpose"
        c1 = jnp.full((1,), 8.0, jnp.float32)
        fn = jax.jit(
            lambda v, n: tdk.batched_adi_kernel(
                v[None], c1, c1, steps=n, panel=cand.bm,
                variant=variant)[0],
            static_argnums=1)
        lo_a, hi_a = max(lo // 50, 2), max(hi // 50, 20)
        first = timed_call(fn, u, lo_a)
        warmup = first.warmup_s
        if compile_timeout_s is not None and warmup is not None \
                and warmup > compile_timeout_s:
            return MeasureOutcome(
                cand, "timeout", warmup_s=warmup,
                error=f"compile+warmup {warmup:.1f}s over the "
                      f"{compile_timeout_s:.0f}s wall")
        step = min_of_two_point(fn, u, lo_a, hi_a, reps=reps)
        return MeasureOutcome(
            cand, "ok", step_time_s=step,
            mcells_per_s=(problem.nx - 2) * (problem.ny - 2)
            / step / 1e6,
            warmup_s=warmup)
    if cand.route == "vmem":
        fn = jax.jit(lambda v, n: ps.multi_step_vmem(v, n, 0.1, 0.1),
                     static_argnums=1)
    elif cand.route == "C":
        fn = _legacy_chunk_fn(cand.bm, cand.tsteps, 0.1, 0.1)
    else:
        fn = jax.jit(
            lambda v, n: ps.band_chunk(v, n, 0.1, 0.1,
                                       tsteps=cand.tsteps, bm=cand.bm),
            static_argnums=1)

    # Compile-wall guard: the first (warmup) call pays compile + program
    # load. A soft wall is the honest option in-process — the cost is
    # already sunk when we notice — but a run that blew the wall is
    # recorded as such so resume never pays it again.
    first = timed_call(fn, u, lo)
    warmup = first.warmup_s
    if compile_timeout_s is not None and warmup is not None \
            and warmup > compile_timeout_s:
        return MeasureOutcome(cand, "timeout", warmup_s=warmup,
                              error=f"compile+warmup {warmup:.1f}s over "
                                    f"the {compile_timeout_s:.0f}s wall")
    ts_lo = [first.elapsed] + [timed_call(fn, u, lo, warmup=False).elapsed
                               for _ in range(reps - 1)]
    hi_first = timed_call(fn, u, hi)
    ts_hi = [hi_first.elapsed] + [
        timed_call(fn, u, hi, warmup=False).elapsed
        for _ in range(reps - 1)]
    step = (min(ts_hi) - min(ts_lo)) / (hi - lo)
    return MeasureOutcome(
        cand, "ok", step_time_s=step,
        mcells_per_s=(problem.nx - 2) * (problem.ny - 2) / step / 1e6,
        warmup_s=warmup)


def measure_candidate(problem: Problem, cand: Candidate, *, u=None,
                      backend=None, lo: int = 4000, hi: int = 20000,
                      reps: int = 4, compile_timeout_s: float = 300.0,
                      registry=None) -> MeasureOutcome:
    """Measure one search point: deterministic simulated backend when
    given (CPU-testable search logic), the attached device otherwise
    (``u`` is the initial grid, built if omitted). Failures come back
    classified in the outcome — a search never crashes on one bad
    point."""
    t0 = time.perf_counter()
    try:
        if backend is not None:
            step = backend.step_time(problem, cand)
            out = MeasureOutcome(
                cand, "ok", step_time_s=step,
                mcells_per_s=(problem.nx - 2) * (problem.ny - 2)
                / step / 1e6)
        else:
            if u is None and cand.route != "fused":
                # (fused measures on its own sharded mesh state —
                # _fused_mesh_fn — so a full-grid build here would be
                # allocated only to be discarded.)
                from heat2d_tpu.ops import inidat
                import jax
                u = jax.block_until_ready(inidat(problem.nx, problem.ny))
            out = _measure_real(u, problem, cand, lo=lo, hi=hi,
                                reps=reps,
                                compile_timeout_s=compile_timeout_s)
    except Exception as e:  # noqa: BLE001 — classify and carry on
        out = MeasureOutcome(cand, classify_failure(e),
                             error=f"{type(e).__name__}: {e}")
    if registry is not None:
        registry.counter("tune_points_measured_total",
                         status=out.status)
        registry.observe("tune_measure_s", time.perf_counter() - t0)
    return out


# --------------------------------------------------------------------- #
# Simulated backend
# --------------------------------------------------------------------- #

class SimulatedOOM(RuntimeError):
    """Simulated scoped-VMEM compile OOM."""


class SimulatedCompileError(RuntimeError):
    """Simulated Mosaic lowering failure."""


class SimulatedBackend:
    """Deterministic analytic step-time model of the band kernels —
    NOT a performance oracle; a stand-in with the right *shape* (an
    interior optimum over bm, a T payoff with diminishing returns, an
    envelope that fails hard) so the search/db/resume logic and its
    tests run on CPU in milliseconds and always reproduce bit-identical
    frontiers.

    Model: per-step cost = compute (VPU) + HBM stream (2 x grid
    bytes / T, inflated by the halo-recompute tax (bm + 2T)/bm and the
    pad tax ceil(nx/bm)*bm/nx) + a per-program launch term
    (ceil(nx/bm)/T); legacy C additionally pays the non-overlapped
    strip-gather C2 eliminates (2T/bm of the grid per sweep); the vmem
    route is compute-only. Deeper/taller therefore wins until a
    failure mode bites — exactly the real trade — and the failure
    modes mirror the chip: working-set estimate over the 14 MB hard
    limit -> SimulatedOOM; C2 windows past the probed envelope table
    -> SimulatedCompileError.
    """

    device_kind = "sim-v5e"
    HBM_BYTES_PER_S = 800e9
    VPU_CELLS_PER_S = 8e11
    LAUNCH_S_PER_PROGRAM = 3e-7
    HARD_LIMIT_BYTES = 14 * 2 ** 20
    #: ICI link bandwidth for the fused-route model (per-direction,
    #: v5e-class order of magnitude — the model only needs the right
    #: SHAPE: a fixed per-step edge-traffic term the interior sweep can
    #: hide, a seam-recompute tax growing with T, and a launch term
    #: shrinking with T, so the depth has an interior optimum).
    ICI_BYTES_PER_S = LINK_BYTES_PER_S["ici"]
    #: ext-row compile envelope per row width (the probed-table analogue)
    EXT_ROWS = {32 * 1024: 64, 16 * 1024: 176, 8 * 1024: 336}

    def __init__(self, link: str = "ici"):
        """``link`` classifies the seam the fused route's edge
        traffic crosses (the multihost asymmetry): 'ici' is the
        historical default — every existing frontier reproduces
        bit-identically — while 'dcn' prices the same per-step
        strips at the cross-host bandwidth, so depth tuning SEES
        that a DCN seam is ~7x harder to hide under the interior
        sweep and pays off deeper T before the seam tax wins."""
        self.link = link
        self.link_bytes_per_s = link_bytes_per_s(link)

    def step_time(self, problem: Problem, cand: Candidate) -> float:
        nx, ny, itemsize = problem.nx, problem.ny, problem.itemsize
        grid_bytes = nx * ny * itemsize
        compute = problem.cells / self.VPU_CELLS_PER_S
        if cand.route == "fused":
            # Per-SHARD model of the overlap route: interior compute
            # hides the (per-step-constant) edge traffic; the boundary
            # frames recompute ~6T(bm+bn) cells per step (the seam
            # tax); one kernel launch per T-step chunk.
            t = cand.tsteps
            if nx <= 2 * t or ny <= 2 * t:
                raise SimulatedCompileError(
                    f"fused overlap frames exceed the {nx}x{ny} shard "
                    f"at T={t}")
            from heat2d_tpu.ops.pallas_stencil import fused_ici_est_bytes
            est = fused_ici_est_bytes(nx, ny, t, itemsize)
            if est > self.HARD_LIMIT_BYTES:
                raise SimulatedOOM(
                    f"fused working set {est / 2**20:.1f} MB over the "
                    f"{self.HARD_LIMIT_BYTES / 2**20:.0f} MB core")
            ici_s = 2 * (nx + ny) * itemsize / self.link_bytes_per_s
            seam = 6 * t * (nx + ny) / problem.cells
            return (max(compute, ici_s) + compute * seam
                    + self.LAUNCH_S_PER_PROGRAM / t)
        if cand.route in ("adi", "adi_s"):
            # Per-ADI-STEP model (a different algorithm — two
            # tridiagonal sweeps + two half-RHS stencils; comparable
            # only within the adi: frontier): ~10 grid passes of HBM
            # stream, a launch term shrinking with the panel width,
            # the explicit-transpose variant paying 4 extra transpose
            # passes and the strided variant a lane-serialization
            # compute tax on its second sweep.
            bn = cand.bm
            if bn <= 0 or ny % bn:
                raise SimulatedCompileError(
                    f"adi panel {bn} does not tile the {ny}-lane axis")
            est = 3 * nx * bn * itemsize
            if est > self.HARD_LIMIT_BYTES:
                raise SimulatedOOM(
                    f"tridiag panel {est / 2**20:.1f} MB over the "
                    f"{self.HARD_LIMIT_BYTES / 2**20:.0f} MB core")
            adi_compute = 8 * problem.cells / self.VPU_CELLS_PER_S
            stream = 10 * grid_bytes / self.HBM_BYTES_PER_S
            launches = -(-ny // bn) + -(-nx // bn)
            if cand.route == "adi":
                stream += 4 * grid_bytes / self.HBM_BYTES_PER_S
            else:
                adi_compute += 64 * problem.cells / self.VPU_CELLS_PER_S
            return (adi_compute + stream
                    + launches * self.LAUNCH_S_PER_PROGRAM)
        if cand.route == "vmem":
            if 3 * grid_bytes > self.HARD_LIMIT_BYTES // 2:
                raise SimulatedOOM(
                    f"grid {grid_bytes / 2**20:.1f} MB not VMEM-resident")
            return compute
        bm, t = cand.bm, cand.tsteps
        est = band_est_bytes(bm, t, ny, itemsize)
        if est > self.HARD_LIMIT_BYTES:
            raise SimulatedOOM(
                f"scoped vmem {est / 2**20:.1f} MB over the "
                f"{self.HARD_LIMIT_BYTES / 2**20:.0f} MB core")
        row_bytes = ny * itemsize
        if cand.route == "C2":
            cap = self.EXT_ROWS.get(row_bytes,
                                    max(64, 2 ** 21 // max(row_bytes, 1)))
            if bm + 2 * t > cap:
                raise SimulatedCompileError(
                    f"Mosaic: window of {bm + 2 * t} ext rows over the "
                    f"{cap}-row envelope at {row_bytes} B rows")
        nprog = -(-nx // bm)
        halo_tax = (bm + 2 * t) / bm
        pad_tax = nprog * bm / nx
        stream = (2 * grid_bytes / t * halo_tax * pad_tax
                  / self.HBM_BYTES_PER_S)
        if cand.route == "C":
            # The non-overlapped per-sweep strip gather C2 eliminates.
            stream += 2 * grid_bytes * (2 * t / bm) / t \
                / self.HBM_BYTES_PER_S
        return (compute * halo_tax * pad_tax + stream
                + nprog * self.LAUNCH_S_PER_PROGRAM / t)
