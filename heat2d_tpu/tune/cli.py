"""``heat2d-tpu-tune`` — run/resume a kernel search, print the
frontier, export the db.

The search loop per shape: build the candidate space (pruned by the
VMEM resource model before anything compiles), skip points the db
already holds a terminal result for (RESUME — a killed search loses at
most the point in flight), measure the rest under probe mode (the VMEM
hard limit lifted and restored by the ``probe_limits`` context
manager), record every outcome into the db with an atomic save after
each point, then stamp the best ``(route, bm, T)`` + measured Mcells/s
+ provenance as the entry consumers (``band_chunk``, the serve engine's
per-signature pre-resolve) look up.

``--selftest`` runs the whole loop twice on the deterministic simulated
backend (CPU-safe, milliseconds): the first pass must write a db and
stamp a best config per shape; the second must be a PURE cache hit
(zero measurements); and the printed frontier table must match the
stored entries. The CI ``tune-selftest`` job runs exactly this.
"""

from __future__ import annotations

import argparse
import os
import sys

from heat2d_tpu.tune.db import DB_SCHEMA, TuningDB, current_salt
from heat2d_tpu.tune.measure import (TERMINAL_STATUSES, SimulatedBackend,
                                     measure_candidate, probe_limits)
from heat2d_tpu.tune.space import Candidate, Problem, candidate_space

DEFAULT_DB = "tune_db.json"
#: The selftest's shapes: one VMEM-resident (exercises the vmem route),
#: two streaming (exercise bm/T search, the C2-vs-C split, and —
#: at 8192 columns — simulated envelope failures).
SELFTEST_SHAPES = ((640, 512), (4096, 4096), (4096, 8192))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-tune",
        description="on-device kernel search with a persistent "
                    "per-device tuning database (docs/TUNING.md)")
    p.add_argument("--shapes", default=None, metavar="LIST",
                   help="comma-separated NXxNY shapes to tune "
                        "(e.g. 4096x4096,2560x2048)")
    p.add_argument("--db", default=None, metavar="PATH",
                   help=f"tuning db path (default: $HEAT2D_TUNE_DB or "
                        f"./{DEFAULT_DB})")
    p.add_argument("--routes", default=None, metavar="LIST",
                   help="restrict the search to these routes "
                        "(vmem,C,C2; default all)")
    p.add_argument("--t-ladder", default=None, metavar="LIST",
                   help="comma-separated fused-step depths "
                        "(default 4,8,12,16)")
    p.add_argument("--bm-grid", default=None, metavar="LIST",
                   help="comma-separated band heights (8-aligned; "
                        "default the probe ladder + planner picks)")
    p.add_argument("--lo", type=int, default=4000,
                   help="two-point low step count")
    p.add_argument("--hi", type=int, default=20000,
                   help="two-point high step count")
    p.add_argument("--reps", type=int, default=4,
                   help="min-of-reps per point")
    p.add_argument("--compile-timeout", type=float, default=300.0,
                   metavar="S",
                   help="soft compile+warmup wall per point; points "
                        "over it record status=timeout and are never "
                        "re-attempted on resume")
    p.add_argument("--probe-past-envelope", action="store_true",
                   help="keep resource-model rejects in the search "
                        "(the envelope-probing mode; failures are the "
                        "datum)")
    p.add_argument("--simulate", action="store_true",
                   help="measure on the deterministic simulated "
                        "backend instead of the attached device "
                        "(search-logic testing; CPU-safe)")
    p.add_argument("--selftest", action="store_true",
                   help="end-to-end search/db/resume selftest on the "
                        "simulated backend; exit nonzero on any "
                        "invariant failure")
    p.add_argument("--print", dest="print_only", action="store_true",
                   help="print the frontier table from the stored db "
                        "without measuring anything")
    p.add_argument("--merge", nargs="+", default=None, metavar="DB",
                   help="merge these tuning dbs (fleet-wide "
                        "consolidation: best entry per device kind, "
                        "shape:dtype, salt) and write the result to "
                        "-o/--out")
    p.add_argument("-o", "--out", default=None, metavar="PATH",
                   help="with --merge: output db path (may equal an "
                        "input for in-place consolidation)")
    p.add_argument("--export", default=None, metavar="PATH",
                   help="write the db document (pretty JSON) here "
                        "after the run")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write telemetry JSONL (tune_* metric "
                        "families + a kind='tune' run record)")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                   help="force a JAX platform")
    return p


def _parse_shapes(arg: str):
    out = []
    for tok in arg.split(","):
        nx, ny = tok.lower().split("x")
        out.append((int(nx), int(ny)))
    return out


def _device_kind(backend) -> str:
    if backend is not None:
        return backend.device_kind
    from heat2d_tpu.ops import pallas_stencil as ps
    return ps._vmem_total()[1]


def search_problem(db: TuningDB, problem: Problem, *, backend=None,
                   routes=None, bm_grid=None, t_ladder=None, lo=4000,
                   hi=20000, reps=4, compile_timeout_s=300.0,
                   probe_past_envelope=False, registry=None,
                   out=sys.stdout) -> dict:
    """Search one shape, resuming from the db. Returns the summary
    {"measured": n, "cached": n, "failed": n, "best": point|None}."""
    kind = _device_kind(backend)
    key = problem.key()

    def key_for(c):
        # Fused points measure a multi-chip mesh program, ADI points a
        # different ALGORITHM's per-step cost — each lives in its own
        # frontier so neither can win (or be shadowed by) the
        # single-chip explicit best; see Problem.fused_key/adi_key.
        if c.route == "fused":
            return problem.fused_key()
        if c.route.startswith("adi"):
            return problem.adi_key()
        return key

    cands, pruned = candidate_space(
        problem, routes=routes, bm_grid=bm_grid, t_ladder=t_ladder,
        probe_past_envelope=probe_past_envelope,
        assume_tpu=backend is not None)
    # Never clobber a real measurement with a prune note: a prior
    # --probe-past-envelope run may hold measured data for points the
    # conservative model rejects (review r6).
    measured_already = {
        k: db.measured_keys(
            kind, k, ("ok", "oom", "compile_error", "timeout", "error"))
        for k in (key, problem.fused_key(), problem.adi_key())}
    wrote_pruned = False
    for c, reason in pruned:
        if (c.route, c.bm, c.tsteps) in measured_already[key_for(c)]:
            continue
        db.record_point(kind, key_for(c),
                        {"route": c.route, "bm": c.bm,
                         "tsteps": c.tsteps, "status": "pruned",
                         "error": reason})
        wrote_pruned = True
    if wrote_pruned:
        db.save()      # pruned-only shapes still leave their trace
    # Under --probe-past-envelope a previously-PRUNED point is exactly
    # what the user asked to measure — only real measurement outcomes
    # count as terminal then (review r6).
    terminal = (tuple(s for s in TERMINAL_STATUSES if s != "pruned")
                if probe_past_envelope else TERMINAL_STATUSES)
    done = {k: db.measured_keys(kind, k, terminal)
            for k in (key, problem.fused_key(), problem.adi_key())}
    measured = failed = cached = 0
    u = None
    if backend is None and any(
            (c.route, c.bm, c.tsteps) not in done[key_for(c)]
            and c.route != "fused" for c in cands):
        import jax
        from heat2d_tpu.ops import inidat
        u = jax.block_until_ready(inidat(problem.nx, problem.ny))
    with probe_limits("lifted by the heat2d-tpu-tune probe"):
        for c in cands:
            if (c.route, c.bm, c.tsteps) in done[key_for(c)]:
                cached += 1
                continue
            outc = measure_candidate(
                problem, c, u=u, backend=backend, lo=lo, hi=hi,
                reps=reps, compile_timeout_s=compile_timeout_s,
                registry=registry)
            db.record_point(kind, key_for(c), outc.to_point())
            db.save()          # crash-safe resume: one point at risk
            measured += 1
            if outc.status != "ok":
                failed += 1
                print(f"  {problem.key():>18} {c.label():<18} "
                      f"{outc.status}: {outc.error}", file=out)
            else:
                print(f"  {problem.key():>18} {c.label():<18} "
                      f"step={outc.step_time_s:.3e}s "
                      f"{outc.mcells_per_s:10.1f} Mcells/s", file=out)
    if registry is not None and cached:
        registry.counter("tune_points_cached_total", value=cached)

    best = None
    for k in (key, problem.fused_key(), problem.adi_key()):
        entry = db.entry(kind, k)
        ok_points = [p for p in (entry or {}).get("points", [])
                     if p.get("status") == "ok"]
        if not ok_points:
            continue
        k_best = max(ok_points, key=lambda p: p["mcells_per_s"])
        db.set_best(
            kind, k,
            {"route": k_best["route"], "bm": k_best["bm"],
             "tsteps": k_best["tsteps"]},
            k_best["mcells_per_s"],
            _provenance(backend, lo, hi, reps))
        db.save()
        if registry is not None:
            registry.gauge("tune_best_mcells_per_s",
                           k_best["mcells_per_s"], shape=k)
        if k == key:
            best = k_best
    return {"problem": key, "measured": measured, "cached": cached,
            "failed": failed, "best": best}


def _provenance(backend, lo, hi, reps) -> dict:
    import datetime

    prov = {
        "protocol": f"two-point {lo}->{hi} steps, min of {reps}",
        "backend": ("simulated" if backend is not None
                    else "device"),
        "salt": current_salt(),
        "timestamp": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    if backend is None:
        import jax
        prov["jax_version"] = jax.__version__
    return prov


def frontier_table(db: TuningDB, device_kind: str) -> str:
    """The stored frontier: one row per (shape, measured point), ok
    points ranked by rate, the stamped best tagged — everything printed
    comes from the db, so the table doubles as a dump consumers can
    diff against the entries."""
    lines = [f"# tuning frontier — {device_kind} "
             f"(salt {current_salt()})",
             f"{'shape:dtype':>20} {'route':<5} {'bm':>4} {'T':>3} "
             f"{'step (s)':>11} {'Mcells/s':>10}  status"]
    entries = (db.data["devices"].get(device_kind, {})
               .get("entries", {}))
    for key in sorted(entries):
        e = db.entry(device_kind, key)
        if e is None:
            continue
        best = e.get("best") or {}
        # rollout provenance (docs/CONTROL.md): entries staged by the
        # control plane carry validated/epoch stamps — surfaced on the
        # best row so the frontier shows what production actually
        # proved vs what a search merely measured
        vtag = ""
        if "validated" in e or "epoch" in e:
            # missing 'validated' defaults True (the incumbent
            # back-compat rule every other consumer applies)
            kind_tag = ("validated" if e.get("validated", True)
                        else "candidate")
            vtag = f" [{kind_tag} e{int(e.get('epoch', 0))}]"
        pts = sorted(e.get("points", []),
                     key=lambda p: -(p.get("mcells_per_s") or 0))
        for p in pts:
            is_best = (best and p.get("status") == "ok"
                       and (p["route"], p["bm"], p["tsteps"])
                       == (best.get("route"), best.get("bm"),
                           best.get("tsteps")))
            st = p.get("step_time_s")
            mc = p.get("mcells_per_s")
            lines.append(
                f"{key:>20} {p.get('route', '?'):<5} "
                f"{p.get('bm', 0):>4} {p.get('tsteps', 0):>3} "
                f"{f'{st:.3e}' if st is not None else '—':>11} "
                f"{f'{mc:.1f}' if mc is not None else '—':>10}  "
                f"{p.get('status')}"
                f"{'  <-- best' + vtag if is_best else ''}")
    return "\n".join(lines)


def run_search(args, registry=None, out=sys.stdout) -> int:
    backend = SimulatedBackend() if args.simulate else None
    db_path = args.db or os.environ.get("HEAT2D_TUNE_DB", DEFAULT_DB)
    db = TuningDB(db_path)
    kind = _device_kind(backend)
    shapes = _parse_shapes(args.shapes) if args.shapes else \
        [(4096, 4096)]
    routes = args.routes.split(",") if args.routes else None
    t_ladder = ([int(v) for v in args.t_ladder.split(",")]
                if args.t_ladder else None)
    bm_grid = ([int(v) for v in args.bm_grid.split(",")]
               if args.bm_grid else None)

    print(f"# search on {kind}; db={db_path} (salt {current_salt()})",
          file=out)
    totals = {"measured": 0, "cached": 0, "failed": 0}
    for nx, ny in shapes:
        s = search_problem(
            db, Problem(nx, ny), backend=backend, routes=routes,
            bm_grid=bm_grid, t_ladder=t_ladder, lo=args.lo, hi=args.hi,
            reps=args.reps, compile_timeout_s=args.compile_timeout,
            probe_past_envelope=args.probe_past_envelope,
            registry=registry, out=out)
        for k in totals:
            totals[k] += s[k]
        b = s["best"]
        print(f"# {s['problem']}: best "
              + (f"{b['route']} bm={b['bm']} T={b['tsteps']} "
                 f"{b['mcells_per_s']:.1f} Mcells/s" if b else "none")
              + f" (measured {s['measured']}, cached {s['cached']}, "
                f"failed {s['failed']})", file=out)
    print(frontier_table(db, kind), file=out)
    print(f"# totals: measured={totals['measured']} "
          f"cached={totals['cached']} failed={totals['failed']}",
          file=out)
    if args.export:
        from heat2d_tpu.io.binary import write_json_atomic
        write_json_atomic(db.data, args.export, sort_keys=True)
        print(f"# exported db to {args.export}", file=out)
    _write_metrics(args, registry, totals)
    return 0


def run_selftest(args, registry=None) -> int:
    """Search -> db -> resume -> frontier, all on the simulated
    backend. Asserts: a db file is produced with a stamped best per
    shape; a second run is a PURE cache hit (zero measurements); the
    frontier table matches the stored entries."""
    import tempfile

    backend = SimulatedBackend()
    db_path = args.db or os.path.join(tempfile.mkdtemp("heat2d-tune"),
                                      "tune_db.json")
    if os.path.exists(db_path):
        # The selftest's invariants assume a COLD start (first pass
        # must measure, second must cache); a warm db from a previous
        # selftest would fail them spuriously. The path is the
        # selftest's own artifact — start it fresh.
        os.remove(db_path)
        print(f"# selftest: removed pre-existing db at {db_path} "
              f"(cold-start invariants)")
    failures = []
    shapes = (_parse_shapes(args.shapes) if args.shapes
              else SELFTEST_SHAPES)

    # probe_past_envelope: resource-model rejects are MEASURED (the
    # simulated backend raises its OOM/compile failures), exercising
    # the failure-class capture end to end.
    db = TuningDB(db_path)
    first = [search_problem(db, Problem(nx, ny), backend=backend,
                            probe_past_envelope=True,
                            registry=registry)
             for nx, ny in shapes]
    if not os.path.exists(db_path):
        failures.append(f"no db written at {db_path}")
    if not any(s["measured"] for s in first):
        failures.append("first pass measured nothing")
    if any(s["best"] is None for s in first):
        failures.append(f"a shape has no best config: {first}")
    if not any(s["failed"] for s in first):
        failures.append("no candidate exercised a failure class "
                        "(envelope model dead?)")
    # The fused halo route must be part of the search: at least one
    # shape must land a measured-ok fused point in the db (the entry
    # runtime.fused_config serves), and the resume purity check below
    # then proves fused points resume from the db like every other
    # route.
    kind0 = backend.device_kind
    fused_db = TuningDB(db_path)
    fused_entries = [fused_db.entry(kind0, Problem(nx, ny).fused_key())
                     for nx, ny in shapes]
    fused_pts = [p for e in fused_entries if e
                 for p in e.get("points", [])]
    if not any(p.get("status") == "ok" for p in fused_pts):
        failures.append("no fused-route point measured ok "
                        f"(fused points: {fused_pts})")
    # ...and the fused frontier stamps its own best, under its own
    # key, so a global-mesh rate can never shadow the single-chip best.
    if not any((e or {}).get("best", {}).get("route") == "fused"
               for e in fused_entries):
        failures.append("no fused-frontier best stamped under a "
                        "fused: key")

    # Resume: a FRESH db object against the same file must skip every
    # completed point (the crash-resume contract).
    db2 = TuningDB(db_path)
    second = [search_problem(db2, Problem(nx, ny), backend=backend,
                             probe_past_envelope=True,
                             registry=registry)
              for nx, ny in shapes]
    if any(s["measured"] for s in second):
        failures.append(f"second run re-measured points: {second}")
    if not all(s["cached"] for s in second):
        failures.append("second run reported no cached points")

    # The frontier table is derived from the stored entries alone; each
    # shape's stamped best must appear as a tagged row.
    table = frontier_table(db2, backend.device_kind)
    print(table)
    for nx, ny in shapes:
        e = db2.entry(backend.device_kind, Problem(nx, ny).key())
        b = (e or {}).get("best")
        if not b:
            failures.append(f"no stored best for {nx}x{ny}")
            continue
        want = (f"{b['route']:<5} {b['bm']:>4} {b['tsteps']:>3}")
        tagged = [ln for ln in table.splitlines()
                  if "<-- best" in ln
                  and ln.lstrip().startswith(f"{nx}x{ny}:")]
        if len(tagged) != 1 or want not in tagged[0]:
            failures.append(
                f"frontier best row for {nx}x{ny} does not match the "
                f"stored entry {b}: {tagged}")

    # Determinism: the simulated backend must reproduce the exact
    # stored rates (a drifting model would silently break resume).
    probe = Problem(*shapes[-1])
    e = db2.entry(backend.device_kind, probe.key())
    for p in e["points"]:
        if p["status"] != "ok":
            continue
        again = measure_candidate(
            probe, Candidate(p["route"], p["bm"], p["tsteps"]),
            backend=backend)
        if again.step_time_s != p["step_time_s"]:
            failures.append(f"simulated backend non-deterministic at "
                            f"{p}")
            break

    summary = {"measured": sum(s["measured"] for s in first),
               "cached_on_resume": sum(s["cached"] for s in second),
               "failures": failures}
    print(f"# selftest: measured {summary['measured']} points, resume "
          f"cached {summary['cached_on_resume']}, db at {db_path}")
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    _write_metrics(args, registry, summary)
    print("selftest " + ("FAILED" if failures else "passed"),
          flush=True)
    return 1 if failures else 0


def _write_metrics(args, registry, extra) -> None:
    from heat2d_tpu.obs.record import write_run_jsonl
    write_run_jsonl(registry, args.metrics_out, "tune", extra)


def run_merge(args, out=sys.stdout) -> int:
    """``--merge a.json b.json -o out.json``: consolidate per-worker
    dbs fleet-wide. Inputs load with the normal corruption tolerance
    (a torn worker db degrades to an empty contribution, flagged in
    the summary); the output commits atomically."""
    if not args.out:
        print("--merge requires -o/--out PATH", file=sys.stderr)
        return 2
    merged = TuningDB(args.out)
    # The output starts EMPTY even if the path exists: the result must
    # be exactly the merge of the named inputs (list the output as an
    # input for read-modify-write consolidation).
    merged.data = {"schema": DB_SCHEMA, "devices": {}}
    merged.corrupt = False
    rc = 0
    for path in args.merge:
        src = TuningDB(path)
        if src.corrupt or (not src.data["devices"]
                           and not os.path.exists(path)):
            print(f"# {path}: unreadable or missing — contributed "
                  f"nothing", file=out)
            rc = 1
            continue
        s = merged.merge(src)
        print(f"# {path}: +{s['entries_added']} entries, "
              f"{s['entries_merged']} merged "
              f"(+{s['points_added']} points), "
              f"{s['entries_kept']} kept", file=out)
    merged.save()
    n = nv = 0
    for d in merged.data["devices"].values():
        for e in d.get("entries", {}).values():
            n += 1
            nv += bool(e.get("validated"))
    print(f"# wrote {args.out}: {n} entries across "
          f"{len(merged.data['devices'])} device kinds"
          f" ({nv} validated)", file=out)
    return rc


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    registry = None
    if args.metrics_out:
        from heat2d_tpu.obs import MetricsRegistry
        registry = MetricsRegistry()
    if args.merge:
        return run_merge(args)
    if args.selftest:
        return run_selftest(args, registry)
    if args.print_only:
        db = TuningDB(args.db
                      or os.environ.get("HEAT2D_TUNE_DB", DEFAULT_DB))
        backend = SimulatedBackend() if args.simulate else None
        for kind in (db.device_kinds() or [_device_kind(backend)]):
            print(frontier_table(db, kind))
        return 0
    return run_search(args, registry)


if __name__ == "__main__":
    sys.exit(main())
