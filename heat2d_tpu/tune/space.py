"""Candidate generation for the kernel search.

A (shape, dtype) problem maps to a list of ``Candidate`` configs over
the three single-chip kernel routes:

- ``vmem`` — the whole-grid-resident multi-step kernel (kernel A); one
  candidate, viable only when the grid passes ``fits_vmem``.
- ``C``    — the legacy gathered-strip temporally-blocked band kernel;
  knobs (bm, T).
- ``C2``   — the gather-free window kernel; knobs (bm, T), plus the
  Mosaic alignment gates (lane-aligned width, 8-aligned bm and T).
- ``fused`` — the fused-halo overlap route (config.halo="fused",
  docs/SCALING.md): the problem shape is the per-SHARD block and the
  knob is the overlap depth T (``tsteps``; the edge-buffer geometry —
  2 T-row strips + 4 lane-padded T-column buffers — follows from it).
  Pruned by the overlap-geometry gate (frames must tile the block:
  bm >= 2T+1) and the kernel-F VMEM working-set estimate
  (``ops.fused_ici_est_bytes``), so the search measures only depths
  the route could actually take.

The bm grid respects the ``plan_bands`` sublane/padding rules (bm is
8-aligned, bm > 2T so a band can amortize its halo) and always includes
the heuristic planners' own picks, so the search can only ever match or
beat the static policy. Candidates whose estimated working set exceeds
the VMEM resource model (``_check_band_vmem`` / the probed C2 envelope)
are pruned BEFORE anything compiles — the search measures the plausible
frontier, not the compiler's failure modes. Probing past the envelope
(what ``benchmarks/tune_bands.py`` exists for) is an explicit flag.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from heat2d_tpu.ops import pallas_stencil as ps

#: The probe ladders the round-3/4 chip campaigns used — the default
#: search axes (tune_bands.py's grid, now shared).
DEFAULT_T_LADDER = (4, 8, 12, 16)
DEFAULT_BM_GRID = (32, 48, 64, 96, 128, 160, 192, 224, 256, 320)

#: "adi"/"adi_s" are the implicit-route tridiagonal kernel's search
#: dimensions (ops/tridiag.py kernel TD): the knob is the lane-panel
#: width (rides in ``bm``), and the route name carries the transpose
#: strategy for the second (y) sweep — "adi" runs an explicit
#: transpose + the same row kernel, "adi_s" the strided lane-
#: elimination pass. Measured step times are PER ADI STEP (a
#: different algorithm — two tridiagonal sweeps + two half-RHS
#: stencils), so the points live under their own ``adi:`` db keys
#: (``Problem.adi_key``) exactly like the fused route's: an implicit
#: per-step rate must never shadow the explicit frontier's best.
ROUTES = ("vmem", "C", "C2", "fused", "adi", "adi_s")

#: Overlap-depth ladder for the fused halo route (candidate T values;
#: the distributed default DEFAULT_HALO_DEPTH=8 rides in the middle).
DEFAULT_FUSED_T_LADDER = (2, 4, 8, 16)

#: Lane-panel ladder for the ADI tridiagonal kernel (panels must tile
#: the member's lane axis exactly — candidates are pruned to
#: divisors; the planner's own pick is seeded in).
DEFAULT_ADI_PANELS = (128, 256, 512, 1024)


@dataclasses.dataclass(frozen=True)
class Problem:
    """A tuning problem: one single-chip stencil workload shape.

    ``problem`` is the spatial-operator family (heat2d_tpu/problems/):
    measured step times are per-FAMILY (a 9-point sweep does different
    arithmetic and halo traffic than the 5-point), so non-heat5
    entries live under a ``<family>:`` key namespace — heat5 keeps the
    legacy ``NXxNY:dtype`` format so every existing db entry keeps
    resolving, and the prefix deliberately breaks the legacy parse so
    family frontiers never shadow the heat5 lookup ladder."""
    nx: int
    ny: int
    dtype: str = "float32"
    problem: str = "heat5"

    def key(self) -> str:
        """The db problem key — shape and dtype (legacy format for
        heat5; ``<family>:NXxNY:dtype`` otherwise); the route rides in
        the candidate/entry, not the key (one frontier per shape)."""
        if self.problem != "heat5":
            return f"{self.problem}:{self.nx}x{self.ny}:{self.dtype}"
        return f"{self.nx}x{self.ny}:{self.dtype}"

    def adi_key(self) -> str:
        """The db key for this shape's ADI (implicit-route) frontier.
        ADI points measure a DIFFERENT algorithm's per-step cost (two
        tridiagonal sweeps + two half-RHS stencils), so they live in
        their own namespace like the fused route's — the prefix
        breaks the "NXxNY:dtype" parse, keeping these entries
        invisible to the band lookup ladder."""
        return f"adi:{self.nx}x{self.ny}:{self.dtype}"

    def fused_key(self) -> str:
        """The db key for this shape's FUSED-route frontier. Fused
        points measure a multi-chip mesh program (global rate over the
        whole mesh, shape = the per-shard block) — recording them into
        the single-chip frontier would let an 8-device rate win the
        cross-route best and shadow the measured band config (or vice
        versa), so they live under their own namespace. The prefix
        deliberately breaks the "NXxNY:dtype" parse, keeping these
        entries invisible to the band lookup ladder's nearest-shape
        tier; ``runtime.fused_config`` queries this key exactly."""
        return f"fused:{self.nx}x{self.ny}:{self.dtype}"

    @property
    def itemsize(self) -> int:
        return jnp.dtype(self.dtype).itemsize

    @property
    def cells(self) -> int:
        return self.nx * self.ny

    @staticmethod
    def from_key(key: str) -> "Problem":
        """Inverse of ``key()``: legacy 2-part keys are heat5;
        3-part keys carry a registered family prefix. The ``adi:`` /
        ``fused:`` route namespaces are NOT problems and stay
        unparseable here on purpose (their prefixes are not family
        names — callers query those keys verbatim)."""
        parts = key.split(":")
        if len(parts) == 3:
            from heat2d_tpu.vocab import PROBLEMS
            fam, shape, dtype = parts
            if fam not in PROBLEMS:
                raise ValueError(
                    f"key {key!r} is not a problem key (prefix "
                    f"{fam!r} is not a registered family)")
            nx, ny = shape.split("x")
            return Problem(int(nx), int(ny), dtype, problem=fam)
        shape, dtype = parts
        nx, ny = shape.split("x")
        return Problem(int(nx), int(ny), dtype)


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the search space. ``bm``/``tsteps`` are 0 for the
    knob-free vmem route (kept integral so the tuple keys JSON/db rows
    cleanly)."""
    route: str
    bm: int = 0
    tsteps: int = 0

    def label(self) -> str:
        if self.route == "vmem":
            return "vmem"
        return f"{self.route} bm={self.bm} T={self.tsteps}"


def band_est_bytes(bm: int, tsteps: int, ny: int, itemsize: int) -> int:
    """The band kernels' working-set estimate — the same expression
    ``_check_band_vmem`` fast-fails on (kept in one place so the pruner
    and the fast-fail can never disagree)."""
    return 5 * (bm + 2 * tsteps) * ny * itemsize


def window_alignment_ok(ny: int, bm: int, tsteps: int) -> bool:
    """The C2 route's SHAPE gates alone (lane-aligned width, 8-aligned
    bm and T, an amortizable core) — ``window_band_viable`` minus the
    live-backend checks, so a simulated search can reason about the
    window route without a TPU attached."""
    return (ny % 128 == 0 and bm % 8 == 0 and tsteps % 8 == 0
            and bm > 2 * tsteps)


def route_for(ny: int, bm: int, tsteps: int, force_legacy: bool = False,
              assume_tpu: bool = False) -> str:
    """Which kernel a (bm, T) band point actually measures —
    ``band_chunk`` routes lane-aligned T=8 configs to the C2 window
    kernel and the rest to legacy C, and an unlabeled table would let
    C2 numbers masquerade as legacy-C measurements (advisor r4).
    ``assume_tpu`` judges by the shape gates alone (the simulated
    backend's view — no real backend consulted)."""
    if force_legacy:
        return "C"
    if assume_tpu:
        return "C2" if window_alignment_ok(ny, bm, tsteps) else "C"
    return "C2" if ps.window_band_viable(ny, bm, tsteps) else "C"


def candidate_space(problem: Problem, routes=None, bm_grid=None,
                    t_ladder=None, probe_past_envelope: bool = False,
                    assume_tpu: bool = False):
    """(candidates, pruned) for ``problem``.

    ``candidates`` is the measurable list; ``pruned`` is a list of
    (candidate, reason) dropped by the resource model — surfaced, not
    silent, so a frontier table can show what was never attempted.
    ``probe_past_envelope`` keeps resource-model rejects in the
    candidate list (the envelope-probing harnesses measure exactly
    those points; the failure class is the datum). ``assume_tpu``
    judges C2 viability by shape gates alone (the simulated backend's
    view).
    """
    routes = ROUTES if routes is None else tuple(routes)
    t_ladder = DEFAULT_T_LADDER if t_ladder is None else tuple(t_ladder)
    bm_grid = DEFAULT_BM_GRID if bm_grid is None else tuple(bm_grid)
    nx, ny, itemsize = problem.nx, problem.ny, problem.itemsize
    limit = ps.vmem_hard_limit_bytes()

    cands: list[Candidate] = []
    pruned: list[tuple[Candidate, str]] = []

    if "vmem" in routes:
        c = Candidate("vmem")
        if ps.fits_vmem((nx, ny), jnp.dtype(problem.dtype)):
            cands.append(c)
        else:
            pruned.append((c, "grid exceeds the VMEM residency budget"))

    if "fused" in routes:
        # Overlap-depth dimension of the fused halo route: the problem
        # shape is the per-shard block; only the depth varies.
        for t in DEFAULT_FUSED_T_LADDER:
            c = Candidate("fused", 0, t)
            if nx <= 2 * t or ny <= 2 * t:
                pruned.append((c, "overlap frames exceed the shard "
                                  "(needs bm > 2T and bn > 2T)"))
            elif (ps.fused_ici_est_bytes(nx, ny, t, itemsize) > limit
                  and not probe_past_envelope):
                est = ps.fused_ici_est_bytes(nx, ny, t, itemsize)
                pruned.append((c, f"fused working set "
                                  f"{est / 2**20:.1f} MB over the "
                                  f"{limit / 2**20:.0f} MB VMEM limit"))
            else:
                cands.append(c)

    adi_routes = [r for r in ("adi", "adi_s") if r in routes]
    if adi_routes:
        # Implicit-route dimension: lane-panel width x transpose
        # strategy (the route name). Knobs ride in bm; tsteps is 0
        # (no temporal blocking — the time loop sits outside the
        # tridiagonal sweeps).
        from heat2d_tpu.ops.tridiag import plan_adi_panel
        panels = set(DEFAULT_ADI_PANELS)
        panels.add(plan_adi_panel(ny))
        for route in adi_routes:
            for bn in sorted(panels):
                c = Candidate(route, bn, 0)
                if bn > ny or ny % bn:
                    pruned.append((c, "panel does not tile the "
                                      "member's lane axis"))
                    continue
                est = 3 * nx * bn * itemsize
                if est > limit and not probe_past_envelope:
                    pruned.append((c, f"tridiag panel working set "
                                      f"{est / 2**20:.1f} MB over the "
                                      f"{limit / 2**20:.0f} MB VMEM "
                                      f"limit"))
                else:
                    cands.append(c)

    # Seed the bm axis with the heuristic planners' own picks so the
    # search result can only match or beat the static policy.
    bms = set(bm_grid)
    bms.add(ps.plan_bands(nx, ny, jnp.dtype(problem.dtype))[0])
    for t in t_ladder:
        if t % 8 == 0:
            bms.add(ps.plan_window_band(nx, ny, t,
                                        jnp.dtype(problem.dtype))[0])

    for t in sorted(t_ladder):
        for bm in sorted(bms):
            if bm % 8 or bm <= 2 * t:
                continue            # sublane rule / no amortizable core
            est = band_est_bytes(bm, t, ny, itemsize)
            over = est > limit
            for route in ("C", "C2"):
                if route not in routes:
                    continue
                c = Candidate(route, bm, t)
                if route == "C2" and route_for(
                        ny, bm, t, assume_tpu=assume_tpu) != "C2":
                    pruned.append((c, "window route not viable "
                                      "(alignment/backend gates)"))
                    continue
                reason = None
                if over:
                    reason = (f"est {est / 2**20:.1f} MB over the "
                              f"{limit / 2**20:.0f} MB VMEM limit")
                elif route == "C2":
                    cap = ps._probed_ext_rows(ny * itemsize)
                    if cap is not None and bm + 2 * t > cap:
                        reason = (f"{bm + 2 * t} ext rows over the "
                                  f"probed {cap}-row compile envelope")
                if reason is None or probe_past_envelope:
                    cands.append(c)
                else:
                    pruned.append((c, reason))
    return cands, pruned
