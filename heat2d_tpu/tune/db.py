"""Persistent per-device tuning database.

A small JSON document, keyed three levels deep:

.. code-block:: text

    devices -> <device_kind> -> entries -> <problem key "NXxNY:dtype">

Each entry carries the best measured config, its measured rate, a
provenance block (protocol, spans, jax version, timestamp), the
code-version **salt** it was measured under, and the full list of
measured points (so a resumed search skips completed work and a
frontier table can be reprinted without re-measuring anything).

Rules the lookup/write paths enforce:

- **Atomic writes** (the ``resil`` idiom): the document is staged to
  ``path + ".tmp"``, fsync'd, and promoted with ``os.replace`` — a
  crash mid-save leaves the previous db intact, never a torn file.
- **Corrupt/torn files are ignored with a warning, not a crash**: a
  tuning db is an accelerant, and a damaged one must degrade to "no
  db", bitwise-identical behavior to an absent file.
- **Code-version salt**: entries are stamped with a hash of the kernel
  source (``ops/pallas_stencil.py``); lookups and resume ignore entries
  whose salt no longer matches — a kernel change silently invalidates
  stale measurements instead of serving them.
- **Three-tier lookup**: exact problem-key hit -> nearest-shape match
  (FLAGGED as ``source="nearest"`` with the matched key; callers
  re-validate it against the resource model) -> ``None`` (callers keep
  the static heuristic — no behavior cliff when the db is absent).
- **Rollout provenance** (the control plane, docs/CONTROL.md): a db
  staged as a rollout CANDIDATE carries a document-level
  ``epoch``/``validated`` stamp (``stamp_rollout``) plus per-entry
  twins (``mark_entries``). Workers report the stamp of the db they
  loaded on their ready line, which is how the chaos gate proves no
  crash-restarted worker ever rejoined on a non-validated config. A
  db without the stamp is the incumbent — ``validated`` defaults to
  True for every db that predates rollouts.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import json
import logging
import math
import os
from typing import Optional

log = logging.getLogger("heat2d_tpu.tune")

DB_SCHEMA = "heat2d-tpu/tune-db/v1"

#: Nearest-shape matches further than this log-distance are not
#: trusted: a 4x shape gap changes which envelope regime applies.
_NEAREST_MAX_DIST = math.log(4.0)

_salt_cache: Optional[str] = None


def current_salt() -> str:
    """Code-version salt: a short hash of the Pallas kernel source.
    Entries measured under a different kernel revision are invisible to
    lookup/resume — the tuned numbers describe code that no longer
    exists."""
    global _salt_cache
    if _salt_cache is None:
        from heat2d_tpu.ops import pallas_stencil
        with open(pallas_stencil.__file__, "rb") as f:
            _salt_cache = hashlib.sha256(f.read()).hexdigest()[:12]
    return _salt_cache


@dataclasses.dataclass(frozen=True)
class TunedConfig:
    """A db answer: the config to use plus where it came from.
    ``source`` is ``"exact"`` or ``"nearest"`` (``matched_key`` then
    names the entry actually matched)."""
    route: str
    bm: int
    tsteps: int
    source: str
    matched_key: str
    mcells_per_s: Optional[float] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _point_key(p: dict) -> tuple:
    return (p.get("route"), int(p.get("bm", 0)), int(p.get("tsteps", 0)))


class TuningDB:
    """The persistent store. All mutation goes through ``record_point``
    / ``set_best`` / ``stamp_device`` + an explicit ``save()`` —
    callers control write frequency (the search saves after every
    point, so a killed search resumes)."""

    def __init__(self, path: str):
        self.path = str(path)
        self.data: dict = {"schema": DB_SCHEMA, "devices": {}}
        self.corrupt = False
        self._load()

    # -- persistence --------------------------------------------------- #

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path) as f:
                data = json.load(f)
            if not isinstance(data, dict) or "devices" not in data:
                raise ValueError("not a tuning db document")
            if data.get("schema") != DB_SCHEMA:
                raise ValueError(
                    f"schema {data.get('schema')!r} != {DB_SCHEMA!r}")
            self.data = data
        except (OSError, ValueError, json.JSONDecodeError) as e:
            # A torn/corrupt db must degrade to "no db", not crash the
            # run it was meant to speed up.
            log.warning("ignoring corrupt tuning db %s (%s) — "
                        "behaving as if no db exists", self.path, e)
            self.corrupt = True

    def save(self) -> None:
        """Atomic commit: temp + fsync + os.replace (the resil
        checkpoint idiom) — a crash mid-save never tears the db.
        An unreadable original (corrupt db, or a path that was never a
        tuning db) is moved aside first, not silently destroyed."""
        if self.corrupt and os.path.exists(self.path):
            aside = self.path + ".corrupt"
            os.replace(self.path, aside)
            log.warning("moved unreadable tuning db aside to %s before "
                        "writing a fresh one", aside)
            self.corrupt = False
        tmp = self.path + ".tmp"
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(self.data, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- structure accessors ------------------------------------------- #

    def device(self, device_kind: str) -> dict:
        return self.data["devices"].setdefault(
            device_kind, {"entries": {}})

    def device_kinds(self) -> list:
        return sorted(self.data["devices"])

    def entry(self, device_kind: str, problem_key: str,
              salted: bool = True) -> Optional[dict]:
        """The entry for an exact problem key, or None. ``salted``
        filters to the current code version (lookup semantics); pass
        False to read stale entries (export/inspection)."""
        e = (self.data["devices"].get(device_kind, {})
             .get("entries", {}).get(problem_key))
        if e is None:
            return None
        if salted and e.get("salt") != current_salt():
            return None
        return e

    def stamp_device(self, device_kind: str, **fields) -> None:
        """Attach device-level facts (e.g. a probed
        ``vmem_total_bytes``) consumers may apply at load time."""
        self.device(device_kind).update(fields)

    # -- rollout provenance (docs/CONTROL.md) --------------------------- #

    @property
    def epoch(self) -> int:
        """The document-level rollout epoch (0 for a db that predates
        rollouts)."""
        return int(self.data.get("epoch", 0) or 0)

    @property
    def validated(self) -> bool:
        """Whether this db is a VALIDATED rollout artifact. Defaults
        True: every db that predates the control plane is the incumbent
        — only a staged candidate is explicitly unvalidated."""
        return bool(self.data.get("validated", True))

    def stamp_rollout(self, *, epoch: int, validated: bool) -> None:
        """Stamp the document-level rollout identity — the stamp a
        fleet worker reports on its ready line (``runtime.
        describe_active``), and the fact the chaos gate asserts on:
        a candidate is ``validated=False`` until its canary survived
        parity + observation; promotion restamps True."""
        self.data["epoch"] = int(epoch)
        self.data["validated"] = bool(validated)

    def mark_entries(self, *, validated: bool, epoch: int) -> int:
        """Stamp every entry's validation provenance (the per-entry
        twin of ``stamp_rollout`` — it travels through ``merge``, where
        a validated entry beats an unvalidated one at equal salt).
        Returns the number of entries stamped."""
        n = 0
        for dev in self.data["devices"].values():
            for e in dev.get("entries", {}).values():
                e["validated"] = bool(validated)
                e["epoch"] = int(epoch)
                n += 1
        return n

    # -- search bookkeeping -------------------------------------------- #

    def _entry_for_write(self, device_kind: str, problem_key: str) -> dict:
        entries = self.device(device_kind)["entries"]
        e = entries.get(problem_key)
        if e is None or e.get("salt") != current_salt():
            # A salt change retires the old points wholesale: resuming
            # onto measurements of dead code would be worse than
            # starting over.
            e = entries[problem_key] = {"salt": current_salt(),
                                        "points": []}
        return e

    def record_point(self, device_kind: str, problem_key: str,
                     point: dict) -> None:
        """Insert-or-replace one measured point (keyed by
        (route, bm, tsteps))."""
        e = self._entry_for_write(device_kind, problem_key)
        k = _point_key(point)
        e["points"] = [p for p in e["points"] if _point_key(p) != k]
        e["points"].append(point)

    def measured_keys(self, device_kind: str, problem_key: str,
                      terminal_statuses) -> set:
        """(route, bm, tsteps) triples a resumed search may skip."""
        e = self.entry(device_kind, problem_key)
        if e is None:
            return set()
        return {_point_key(p) for p in e.get("points", [])
                if p.get("status") in terminal_statuses}

    def set_best(self, device_kind: str, problem_key: str, best: dict,
                 mcells_per_s: float, provenance: dict) -> None:
        e = self._entry_for_write(device_kind, problem_key)
        e["best"] = best
        e["mcells_per_s"] = mcells_per_s
        e["provenance"] = provenance

    # -- fleet-wide consolidation -------------------------------------- #

    def merge(self, other) -> dict:
        """Merge another db (``TuningDB`` or raw document dict) into
        this one — the fleet-wide consolidation primitive: N workers
        each tune against their own db; merging keeps the best entry
        per (device kind, problem key, salt).

        - **Same salt**: points union (per ``(route, bm, tsteps)`` the
          better datum wins — an ``ok`` beats any failure, a faster
          ``ok`` beats a slower one) and the best/provenance restamp
          from the merged frontier. A side that is explicitly a
          rollout CANDIDATE (``validated=False``) never wins the
          best/provenance slots against a validated side — and an
          unstamped entry counts as the validated incumbent —
          chaos/parity-proven beats fast-but-unproven
          (docs/CONTROL.md).
        - **Different salts**: one storage slot per problem key, so the
          CURRENT code version wins; between two stale salts the newer
          provenance timestamp wins (both describe dead code — keep the
          fresher corpse for inspection).
        - Device-level stamps (``vmem_total_bytes`` ...) fill in where
          this db has none; an existing stamp is never overwritten.

        Returns a summary dict (devices / entries added, merged, kept /
        points added) the CLI prints."""
        doc = other.data if isinstance(other, TuningDB) else other
        if not isinstance(doc, dict) or "devices" not in doc:
            raise ValueError("merge source is not a tuning db document")
        s = {"devices": 0, "entries_added": 0, "entries_merged": 0,
             "entries_kept": 0, "points_added": 0}
        for kind, dev in doc.get("devices", {}).items():
            s["devices"] += 1
            mine = self.device(kind)
            for k, v in dev.items():
                if k != "entries":
                    mine.setdefault(k, copy.deepcopy(v))
            for key, theirs in dev.get("entries", {}).items():
                ours = mine["entries"].get(key)
                if ours is None:
                    mine["entries"][key] = copy.deepcopy(theirs)
                    s["entries_added"] += 1
                elif ours.get("salt") == theirs.get("salt"):
                    s["points_added"] += _merge_entry(ours, theirs)
                    s["entries_merged"] += 1
                elif theirs.get("salt") == current_salt() or (
                        ours.get("salt") != current_salt()
                        and _entry_ts(theirs) > _entry_ts(ours)):
                    mine["entries"][key] = copy.deepcopy(theirs)
                    s["entries_added"] += 1
                else:
                    s["entries_kept"] += 1
        return s

    # -- the lookup ladder --------------------------------------------- #

    def lookup(self, device_kind: str, nx: int, ny: int,
               dtype: str = "float32") -> Optional[TunedConfig]:
        """Tier 1: exact (shape, dtype) hit. Tier 2: nearest measured
        shape of the same dtype within a 4x log-distance, flagged
        ``source="nearest"`` (row width dominates the distance — the
        compile envelope is a function of ny, so a same-ny neighbor
        beats a same-nx one). Tier 3 is the caller's: ``None`` means
        'use the static heuristic'."""
        entries = (self.data["devices"].get(device_kind, {})
                   .get("entries", {}))
        key = f"{nx}x{ny}:{dtype}"
        e = self.entry(device_kind, key)
        if e is not None and e.get("best"):
            return self._config(e, "exact", key)

        best_k, best_d = None, None
        for k, cand in entries.items():
            if cand.get("salt") != current_salt() or not cand.get("best"):
                continue
            try:
                shape, dt = k.split(":")
                cnx, cny = (int(v) for v in shape.split("x"))
            except ValueError:
                continue
            if dt != dtype:
                continue
            d = (2.0 * abs(math.log(cny / ny))
                 + abs(math.log(cnx / nx)))
            if d <= _NEAREST_MAX_DIST and (best_d is None or d < best_d):
                best_k, best_d = k, d
        if best_k is not None:
            return self._config(entries[best_k], "nearest", best_k)
        return None

    @staticmethod
    def _config(entry: dict, source: str, key: str) -> TunedConfig:
        b = entry["best"]
        return TunedConfig(route=b.get("route", "C"),
                           bm=int(b.get("bm", 0)),
                           tsteps=int(b.get("tsteps", 0)),
                           source=source, matched_key=key,
                           mcells_per_s=entry.get("mcells_per_s"))


def _entry_ts(e: dict) -> str:
    """ISO timestamps sort lexically; entries without provenance sort
    oldest."""
    return (e.get("provenance") or {}).get("timestamp") or ""


def _better_point(p: dict, q: dict) -> bool:
    """True when measured point ``p`` is the better datum than ``q`` for
    the same (route, bm, tsteps): ``ok`` beats any failure class, and
    among oks the higher min-of-reps rate is the truer capability."""
    p_ok, q_ok = p.get("status") == "ok", q.get("status") == "ok"
    if p_ok != q_ok:
        return p_ok
    if not p_ok:
        return False                     # two failures: keep the first
    return (p.get("mcells_per_s") or 0) > (q.get("mcells_per_s") or 0)


def _merge_entry(ours: dict, theirs: dict) -> int:
    """Union ``theirs``'s points into ``ours`` (same salt) and restamp
    the best from the merged frontier — except that a VALIDATED entry's
    best/provenance beat an unvalidated one's outright (a rollout
    proved that config bitwise-compatible and SLO-clean in production;
    a faster unvalidated point is a claim, not a proof). Returns
    points added."""
    added = 0
    pts = ours.setdefault("points", [])
    have = {_point_key(p): i for i, p in enumerate(pts)}
    for p in theirs.get("points", []):
        k = _point_key(p)
        if k not in have:
            have[k] = len(pts)
            pts.append(copy.deepcopy(p))
            added += 1
        elif _better_point(p, pts[have[k]]):
            pts[have[k]] = copy.deepcopy(p)
    # An UNSTAMPED entry defaults to validated — it is the pre-rollout
    # incumbent (same back-compat rule as TuningDB.validated). Only an
    # explicitly staged candidate (validated=False) loses the
    # preference, so a merge can never let a candidate's faster claim
    # displace an incumbent that predates rollout stamps.
    ours_val = bool(ours.get("validated", True))
    theirs_val = bool(theirs.get("validated", True))
    if ours_val != theirs_val and (ours if ours_val
                                   else theirs).get("best"):
        if theirs_val:
            for k in ("best", "mcells_per_s", "provenance"):
                if k in theirs:
                    ours[k] = copy.deepcopy(theirs[k])
            # the winner's VALIDATION identity must travel too: an
            # unstamped winner leaves the merged entry unstamped
            # (implicitly validated) — keeping the loser's
            # validated=False stamp would let a later candidate merge
            # displace the proven best it just adopted
            for k in ("validated", "epoch"):
                if k in theirs:
                    ours[k] = theirs[k]
                else:
                    ours.pop(k, None)
        # ours validated: keep our best/provenance/stamps as they are
        return added
    ok = [p for p in pts if p.get("status") == "ok"]
    if ok:
        b = max(ok, key=lambda p: p.get("mcells_per_s") or 0)
        best_key = _point_key(b)
        ours["best"] = {"route": b["route"], "bm": b["bm"],
                        "tsteps": b["tsteps"]}
        ours["mcells_per_s"] = b.get("mcells_per_s")
        # the winning measurement's provenance (and rollout stamps)
        # travel with it
        if (_point_key(theirs.get("best") or {}) == best_key
                and theirs.get("provenance")):
            ours["provenance"] = copy.deepcopy(theirs["provenance"])
            for k in ("validated", "epoch"):
                if k in theirs:
                    ours[k] = theirs[k]
    return added
