"""Runtime consultation hook — how tuned configs reach the kernels.

Opt-in, two ways:

- ``HEAT2D_TUNE_DB=/path/to/db.json`` in the environment, or
- ``set_tuning_db(path_or_db)`` in-process (tests, embedding apps).

With neither, every hook returns ``None`` instantly and the planners
behave **bitwise-identically** to a build without this subsystem (the
jaxpr-pinned tests hold that line). With a db, ``band_config`` answers
the planners' "what bm/T/route here?" question through the db's lookup
ladder, RE-VALIDATED against the live resource model (a nearest-shape
or stale-envelope answer must degrade to the heuristic, never to a
compile OOM), and every applied config is recorded so run records can
surface ``tuned_config`` provenance.

Loading a db whose device section carries a probed
``vmem_total_bytes`` stamp also applies it as the VMEM planning budget
(source ``"db"``) — unless an explicit ``--vmem-budget`` flag or
``HEAT2D_VMEM_BUDGET`` env override already won.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from heat2d_tpu.tune.db import TunedConfig, TuningDB

log = logging.getLogger("heat2d_tpu.tune")

ENV_VAR = "HEAT2D_TUNE_DB"

_lock = threading.Lock()
_explicit: Optional[TuningDB] = None
_explicit_set = False
#: (env value, loaded db) — re-resolved whenever the env var changes,
#: so tests (and long-lived processes) can flip it without reloads.
_env_cache: tuple = (None, None)
_applied: dict = {}


def set_tuning_db(db) -> None:
    """Install a db explicitly (a ``TuningDB``, a path, or ``None`` to
    clear back to env-var resolution). Resets applied-config
    provenance."""
    global _explicit, _explicit_set, _env_cache
    with _lock:
        if db is None:
            _explicit, _explicit_set = None, False
        else:
            _explicit = db if isinstance(db, TuningDB) else TuningDB(db)
            _explicit_set = True
            _apply_device_stamps(_explicit)
        _env_cache = (None, None)
        _applied.clear()


def active_db() -> Optional[TuningDB]:
    """The db in force, or None (the default — zero cost, zero behavior
    change)."""
    global _env_cache
    if _explicit_set:
        return _explicit
    env = os.environ.get(ENV_VAR)
    if not env:
        return None
    with _lock:
        cached_env, cached_db = _env_cache
        if cached_env != env:
            db = TuningDB(env)
            if db.corrupt and not db.data["devices"]:
                log.warning("%s=%s is unreadable; tuning disabled for "
                            "this process", ENV_VAR, env)
            _apply_device_stamps(db)
            _env_cache = (env, db)
            return db
        return cached_db


def describe_active() -> Optional[dict]:
    """The active db's rollout identity, or None without a db — the
    stamp a fleet worker reports on its ready line (fleet/worker.py)
    so the control plane can prove which config generation every
    worker is serving (docs/CONTROL.md). ``validated`` defaults True
    for dbs that predate rollouts (they are the incumbent); only a
    staged candidate is explicitly unvalidated."""
    db = active_db()
    if db is None:
        return None
    entries = sum(len(d.get("entries", {}))
                  for d in db.data["devices"].values())
    return {"path": db.path, "epoch": db.epoch,
            "validated": db.validated, "entries": entries}


def _apply_device_stamps(db: TuningDB) -> None:
    """Device-level stamps: a probed ``vmem_total_bytes`` becomes the
    planning budget (source \"db\") unless an explicit flag/env
    override already set one."""
    from heat2d_tpu.ops import pallas_stencil as ps

    kind = ps._vmem_total()[1]
    total = db.data["devices"].get(kind, {}).get("vmem_total_bytes")
    if total and ps.VMEM_BUDGET_BYTES is None \
            and not os.environ.get("HEAT2D_VMEM_BUDGET"):
        try:
            ps.set_vmem_budget(int(total), source="db",
                               origin="set by the tuning db's probed "
                                      "vmem stamp")
        except Exception as e:  # noqa: BLE001 — a bad stamp never fatal
            log.warning("ignoring tuning-db vmem stamp %r: %s", total, e)


def band_config(nrows: int, ny: int, dtype="float32",
                tsteps_hint: Optional[int] = None,
                allow_window: bool = True) -> Optional[TunedConfig]:
    """Tuned (route, bm, T) for a band-kernel problem, or None.
    ``allow_window=False``: the caller compiles the legacy kernel only
    (parity step form, legacy-planner consumers), so a C2 answer is
    relabeled route C before recording — applied-config provenance
    must describe the program that actually compiles.

    The db's answer is re-validated against the LIVE resource model
    before it is allowed to steer a plan — a nearest-shape match or an
    entry probed on other code must fall back to the heuristic rather
    than hand the compiler an over-envelope window:

    - bm must be 8-aligned with bm > 2T (the sublane/amortization
      rules);
    - the working-set estimate must clear the active VMEM hard limit;
    - a C2 answer must pass ``window_band_viable`` and the probed
      ext-row envelope — otherwise it DEGRADES to route C with the
      same (bm, T) when that is itself valid (off-TPU test runs of a
      TPU-tuned db), else to None.
    """
    db = active_db()
    if db is None:
        return None
    import jax.numpy as jnp

    from heat2d_tpu.ops import pallas_stencil as ps
    from heat2d_tpu.tune.space import band_est_bytes

    dt = jnp.dtype(dtype)
    kind = ps._vmem_total()[1]
    cfg = db.lookup(kind, nrows, ny, str(dt))
    if cfg is None or cfg.route == "vmem":
        # The vmem route has no runtime knobs — residency routing
        # already picks it; band planners have nothing to apply.
        return None
    bm, t = cfg.bm, cfg.tsteps or ps.DEFAULT_TSTEPS
    # Validate at the DEEPEST T this answer can end up running under:
    # _resolve_bands callers apply their own sweep depth (band_multi_step
    # and the batched ensemble runner default to DEFAULT_TSTEPS), so a
    # bm validated only against the db's shallower T could fast-fail
    # _check_band_vmem downstream — a crash cliff where the heuristic
    # would have planned a fitting band (review r6).
    t_eff = max(t, tsteps_hint or ps.DEFAULT_TSTEPS)
    if not bm or bm % 8 or bm <= 2 * t_eff:
        return None
    if band_est_bytes(bm, t_eff, ny, dt.itemsize) \
            > ps.vmem_hard_limit_bytes():
        return None
    route = cfg.route
    if route == "C2":
        cap = ps._probed_ext_rows(ny * dt.itemsize)
        if (not allow_window
                or (cap is not None and bm + 2 * t > cap)
                or not ps.window_band_viable(ny, bm, t)):
            route = "C"
    out = TunedConfig(route=route, bm=bm, tsteps=t, source=cfg.source,
                      matched_key=cfg.matched_key,
                      mcells_per_s=cfg.mcells_per_s)
    _record_applied(nrows, ny, str(dt), out)
    return out


def fused_config(bm: int, bn: int,
                 dtype="float32") -> Optional[TunedConfig]:
    """Tuned overlap depth for the FUSED halo route (config.halo=
    'fused'), or None. Keyed by the SHARD block shape ``bm x bn`` —
    the per-device problem the fused kernel/overlap schedule actually
    runs — with route ``"fused"`` and ``tsteps`` = the measured best
    overlap depth T (tune/space.py's fused candidate dimension).

    Consulted only from the fused route's depth planner
    (parallel.sharded.effective_halo_depth), so collective-route
    programs never see it; with no active db it returns None instantly
    (the byte-identical contract). A db answer is RE-VALIDATED against
    the live overlap model before it may steer the schedule:

    - the depth must satisfy the overlap geometry (bm >= 2T, bn >= 2T
      — parallel.halo.fused_halo_viable); a too-deep entry (recorded
      on other hardware or a nearest-shape match) degrades to None
      (the static default depth), never to a broken decomposition;
    - where the in-kernel ICI route would engage (remote DMA
      supported), the kernel-F working-set estimate must clear the
      live VMEM hard limit (ops.fused_ici_est_bytes) — the same
      re-validation discipline band_config applies to C2 entries.
    """
    db = active_db()
    if db is None:
        return None
    from heat2d_tpu.ops import pallas_stencil as ps
    from heat2d_tpu.parallel.halo import fused_halo_viable

    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    kind = ps._vmem_total()[1]
    # Fused entries live in their own "fused:" key namespace (see
    # space.Problem.fused_key: multi-chip mesh rates must never mix
    # into the single-chip frontier) — exact-key only, no nearest tier
    # (a neighboring shard shape's overlap optimum is not trusted);
    # db.entry() already salt-filters stale code versions.
    key = f"fused:{bm}x{bn}:{dt}"
    e = db.entry(kind, key)
    if e is None:
        return None
    b = e.get("best") or {}
    if b.get("route") != "fused":
        return None
    t = int(b.get("tsteps", 0))
    if not t or not fused_halo_viable(bm, bn, t):
        return None
    if (ps.remote_dma_supported()
            and ps.fused_ici_est_bytes(bm, bn, t, dt.itemsize)
            > ps.vmem_hard_limit_bytes()):
        return None
    out = TunedConfig(route="fused", bm=int(b.get("bm", 0)), tsteps=t,
                      source="exact", matched_key=key,
                      mcells_per_s=e.get("mcells_per_s"))
    _record_applied(bm, bn, str(dt), out)
    return out


def adjoint_config(nrows: int, ny: int,
                   dtype="float32") -> Optional[TunedConfig]:
    """The tuning db's answer for a differentiable solve's fused
    forward/recompute segments (heat2d_tpu/diff). The adjoint's band
    route compiles the LEGACY batched band kernel (B=1, traced scalar
    coefficients — models/ensemble._run_batch_band), which plans
    through ``ops._resolve_bands`` and so already CONSUMES the db at
    trace time; this wrapper is the provenance twin: the same
    ``allow_window=False`` lookup, surfaced so inverse run records can
    carry ``tuned_config`` like every other record kind. None when no
    db is active or the entry fails live re-validation — behavior
    then falls back to the heuristic plan, bitwise (the jaxpr-pinned
    contract)."""
    return band_config(nrows, ny, dtype, allow_window=False)


def measured_rate(nx: int, ny: int,
                  dtype: str = "float32") -> Optional[float]:
    """The db's measured Mcells/s for this shape on THIS device kind
    (exact or nearest entry, same lookup ladder as every config
    consult), or None without a db / a stored rate. A RATE, not a
    config: the mesh scheduler and admission control price work with
    it (heat2d_tpu/mesh, docs/SERVING.md) — nothing about the compiled
    program changes, so no live re-validation is needed and the
    jaxpr-pinned free-when-off contract is untouched."""
    db = active_db()
    if db is None:
        return None
    from heat2d_tpu.ops import pallas_stencil as ps

    cfg = db.lookup(ps._vmem_total()[1], nx, ny, dtype)
    if cfg is None or not cfg.mcells_per_s:
        return None
    return float(cfg.mcells_per_s)


def _record_applied(nrows: int, ny: int, dtype: str,
                    cfg: TunedConfig) -> None:
    key = (nrows, ny, dtype)
    with _lock:
        if key not in _applied:
            _applied[key] = {"shape": f"{nrows}x{ny}", "dtype": dtype,
                             **cfg.to_dict()}
            log.info("tuned config applied for %dx%d: route=%s bm=%d "
                     "T=%d (%s via %s)", nrows, ny, cfg.route, cfg.bm,
                     cfg.tsteps, cfg.source, cfg.matched_key)


def applied_configs() -> list:
    """Every tuned config applied by this process so far (deduped by
    shape) — the run records' ``tuned_config`` provenance block."""
    with _lock:
        return [dict(v) for v in _applied.values()]


def reset_applied() -> None:
    with _lock:
        _applied.clear()
