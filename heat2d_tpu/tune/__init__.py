"""Autotune subsystem — on-device kernel search with a persistent
per-device tuning database (docs/TUNING.md).

The Pallas stencil layer's performance-critical choices — band height
``bm``, fused step depth ``T``, kernel route (VMEM-resident / legacy-C
band / C2 window) — started as hardcoded heuristics with "MEASURED
(tune_bands.py probe...)" comments, and the probe harnesses' findings
died in markdown tables. This package closes the loop, Triton/XLA-style:

- ``space``   — declarative candidate generation for a (shape, dtype)
  problem, pruned by the existing VMEM resource model before anything
  compiles.
- ``measure`` — the two-point marginal-step-time protocol as a library
  (single copy; ``benchmarks/sweep.py`` and the ``tune_*`` harnesses
  import it), with failure-class capture and a deterministic simulated
  backend so the search logic is testable on CPU.
- ``db``      — a persistent JSON tuning database keyed by
  (device kind, problem key, code-version salt), atomic writes, and a
  three-tier lookup: exact hit -> nearest-shape (flagged) -> None
  (callers keep today's static heuristics — no behavior cliff when the
  db is absent).
- ``runtime`` — the opt-in consultation hook (``HEAT2D_TUNE_DB``) the
  band planners, the batched ensemble runner, and the serve engine's
  per-signature pre-resolve all go through.
- ``cli``     — ``heat2d-tpu-tune``: run/resume a search, print the
  frontier table, export the db; ``--selftest`` runs the whole loop on
  the simulated backend.
"""

from heat2d_tpu.tune.db import TunedConfig, TuningDB, current_salt
from heat2d_tpu.tune.runtime import (active_db, applied_configs,
                                     set_tuning_db)
from heat2d_tpu.tune.space import Candidate, Problem, candidate_space

__all__ = [
    "Candidate", "Problem", "TunedConfig", "TuningDB", "active_db",
    "applied_configs", "candidate_space", "current_salt",
    "set_tuning_db",
]
