"""Headline benchmark — prints ONE JSON line for the driver.

Metric (BASELINE.md north star): Mcell-updates/s/chip on a 4096x4096 grid,
1000 steps, single chip. ``vs_baseline`` is the ratio against the
reference's best published per-chip figure: its CUDA kernel at 2560x2048,
~669 Mcells/s (Report.pdf p.26 Table 10, derived in BASELINE.md).

Timing follows the reference protocol (SURVEY.md §5.1): compile excluded
(warmup call), fenced with block_until_ready — the cudaEvent pair analogue.
"""

import json
import os
import sys

# Smaller/faster run for smoke-testing: BENCH_QUICK=1.
QUICK = os.environ.get("BENCH_QUICK") == "1"

NX = NY = 1024 if QUICK else 4096
# 24000 steps -> a ~1.5 s two-point span. The round-4 noise study showed
# 0.5 s spans swing +-15% through the tunnel fence's heavy-tailed jitter
# (the same kernel read 178-233k Mcells/s across runs); at >=1.5 s spans
# repeat samples agree within ~1-3%. Well inside the reference's own
# amortization discipline (its CUDA figures average up to 100k
# iterations, Report.pdf p.26 Table 10).
STEPS = 100 if QUICK else 24000
BASELINE_MCELLS = 669.0  # reference CUDA, 2560x2048 (BASELINE.md Table 10)

# The calibrated bound now lives in the package (obs/roofline.py) so
# the serving stack can reach it; imported back here so bench.py's
# public surface is unchanged (tests and BENCH_r* tooling keep their
# import path).
from heat2d_tpu.obs.roofline import (VPU_CALIB_MCELLS,       # noqa: F401,E402
                                     calibrated_bound_mcells)


def build_record(value: float, method: str, elapsed: float,
                 nx: int = None, ny: int = None, steps: int = None,
                 mode: str = "pallas") -> dict:
    """The one JSON line (driver contract), with the self-honesty field:
    pct_of_calibrated_bound says how close the measured number sits to
    the framework's own calibrated structural ceiling — a headline that
    drifts far below it signals a regression; one far above it signals
    a measurement artifact."""
    nx, ny = nx or NX, ny or NY
    rec = {
        "metric": f"Mcells/s/chip {nx}x{ny}x{steps or STEPS} ({mode})",
        "value": round(value, 1),
        "unit": "Mcells/s",
        "vs_baseline": round(value / BASELINE_MCELLS, 2),
        "method": method,
        "end_to_end_s": round(elapsed, 4),
    }
    # Unified record envelope (obs/record.py): schema tag + execution
    # context beside the driver-contract keys above, which stay as-is.
    from heat2d_tpu.obs.record import attach_context
    attach_context(rec, "bench")
    # Wall-clock-to-solution at matched accuracy — the algorithmic-
    # speed headline beside the kernel-speed one (docs/ALGORITHMS.md):
    # explicit at the stability edge vs Crank-Nicolson ADI at 256x the
    # step size to the same t_final, each row carrying
    # time_to_solution_s + accuracy (L2 vs the analytic separable-mode
    # solution). Guarded: a tts failure degrades to an error string,
    # never a lost headline metric.
    try:
        from heat2d_tpu.models import solution
        import jax
        on_tpu = jax.default_backend() == "tpu"
        rec["time_to_solution"] = solution.bench_tts(
            quick=QUICK, on_tpu=on_tpu)
    except Exception as e:  # noqa: BLE001 — record, don't lose bench
        rec["time_to_solution"] = {"error": f"{type(e).__name__}: {e}"}
    # ROADMAP item 2's headline efficiency rows (obs/roofline.py):
    # analytic HBM bytes one cell-update moves on this route, and its
    # reciprocal — the metric any bf16/temporal-blocking claim must
    # move. Structural (throughput-independent) by design; guarded
    # like the tts block so a model gap never loses the headline.
    try:
        from heat2d_tpu.obs import roofline
        m = roofline.analytic_bytes_per_cell_step(nx, ny, method=mode)
        rec["bytes_per_cell_step"] = round(m["bytes_per_cell_step"], 4)
        rec["mcells_per_hbm_byte"] = round(
            1.0 / (1e6 * m["bytes_per_cell_step"]), 9)
    except Exception as e:  # noqa: BLE001 — record, don't lose bench
        rec["bytes_per_cell_step"] = {"error":
                                      f"{type(e).__name__}: {e}"}
    bound = calibrated_bound_mcells(nx, ny)
    if bound is not None and method == "two-point" and mode == "pallas":
        # Only the pallas route's two-point marginal is comparable to
        # the calibrated window-route ceiling — the single-run fallback
        # is fence-dominated, and other modes measure different
        # kernels; either pct would read as a fake regression.
        rec["pct_of_calibrated_bound"] = round(100.0 * value / bound, 1)
    return rec


def main() -> int:
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.solver import Heat2DSolver
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchmarks.sweep import two_point_estimate

    mode = os.environ.get("BENCH_MODE", "pallas")

    # Two-point measurement: the timing fence (utils/timing._fence — a
    # host readback that guarantees completion through remote-tunneled
    # runtimes) costs a fixed ~0.1-0.2 s per timed call. The reference's
    # headline CUDA figure is *per-step* (cudaEvent pair amortized over
    # up to 100k launches, Report.pdf p.26 Table 10), so the like-for-like
    # number is the marginal throughput between two step counts — fixed
    # overhead cancels. The estimator (shared with benchmarks/sweep.py)
    # min-of-3 samples the lo point, min-of-2 the hi point, and applies
    # the decade-confirmation/noise-floor rules.
    solvers = {}

    def timed_run(steps):
        # First call per step count compiles + warms up; repeats skip the
        # untimed priming run (the solver cache keeps the compiled runner).
        fresh = steps not in solvers
        if fresh:
            cfg = HeatConfig(nxprob=NX, nyprob=NY, steps=steps, mode=mode)
            solvers[steps] = Heat2DSolver(cfg)
        return solvers[steps].run(timed=True, warmup=fresh)

    lo = max(STEPS // 5, 1)
    step_time, _hi, result = two_point_estimate(timed_run, lo, STEPS, STEPS)

    # sanity: physics must be non-vacuous (unlike the reference CUDA run —
    # SURVEY.md A.1): interior evolved, boundary clamped at zero.
    u = result.u
    assert float(u[1:-1, 1:-1].max()) > 0.0, "interior wiped — vacuous run"
    assert float(abs(u[0]).max()) == 0.0, "boundary not clamped"

    if step_time is not None:
        value = NX * NY / step_time / 1e6
        method = "two-point"   # fixed fence overhead cancelled
    else:
        # Difference is within noise — report the distorted-but-honest
        # end-to-end figure and say so.
        value = result.mcells_per_s
        method = "single-run (two-point within noise)"
    print(json.dumps(build_record(value, method, result.elapsed,
                                  mode=mode)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
